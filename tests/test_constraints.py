"""Constraint plane (karpenter_tpu/constraints + the ops/binpack
constraint operands): compiler units, kernel semantics, XLA == numpy
bitwise parity, absent-operand wire compat, and the seeded property pin
that batched constrained verdicts equal independent per-group solves."""

import dataclasses

import jax
import numpy as np
import pytest

from karpenter_tpu.api.core import (
    Container,
    ObjectMeta,
    Pod,
    PodSpec,
    RESERVATION_LABEL,
    ZONE_LABEL,
    reservation_of,
    resource_list,
    zone_of,
)
from karpenter_tpu.constraints import (
    ConstraintGroup,
    SpreadSpec,
    canonical_constraints,
    compile_membership,
    compile_rows,
    constraint_meta,
    reservation_fill,
    spread_skew,
    validate_constraints,
)
from karpenter_tpu.metrics.producers.pendingcapacity import encode_snapshot
from karpenter_tpu.metrics.producers.pendingcapacity import (
    encoder as encoder_mod,
)
from karpenter_tpu.ops import binpack as B
from karpenter_tpu.ops.numpy_binpack import binpack_numpy
from karpenter_tpu.store.columnar import snapshot_from_pods


# -- world builders ----------------------------------------------------------


def _pod(name, labels=None, cpu="1"):
    return Pod(
        metadata=ObjectMeta(name=name, labels=dict(labels or {})),
        spec=PodSpec(
            node_name="",
            containers=[
                Container(requests=resource_list(cpu=cpu, memory="1Gi"))
            ],
        ),
    )


def _profile(zone="", reservation="", cpu=8.0):
    labels = set()
    if zone:
        labels.add((ZONE_LABEL, zone))
    if reservation:
        labels.add((RESERVATION_LABEL, reservation))
    return (
        {"cpu": cpu, "memory": 32.0, "pods": 32.0},
        labels,
        set(),
    )


def _random_world(rng, n_groups_spec=3):
    """(pods, profiles, groups): a random fleet whose constraint specs
    exercise every operand family."""
    zones = ["z1", "z2", "z3"][: int(rng.integers(2, 4))]
    profiles = [_profile(zone=z) for z in zones]
    profiles.append(_profile(reservation="gold"))
    profiles.append(_profile())  # zone-less open capacity
    groups = []
    kinds = rng.permutation(
        ["spread", "reservation", "anti", "compact"]
    )[:n_groups_spec]
    for i, kind in enumerate(kinds):
        sel = {"team": f"t{i}"}
        if kind == "spread":
            groups.append(
                ConstraintGroup(
                    name=f"g{i}", pod_selector=sel, spread=SpreadSpec()
                )
            )
        elif kind == "reservation":
            groups.append(
                ConstraintGroup(
                    name=f"g{i}", pod_selector=sel, reservation="gold"
                )
            )
        elif kind == "anti":
            groups.append(
                ConstraintGroup(
                    name=f"g{i}", pod_selector=sel, anti_affinity=True
                )
            )
        else:
            groups.append(
                ConstraintGroup(
                    name=f"g{i}", pod_selector=sel, compact=True
                )
            )
    pods = []
    for p in range(int(rng.integers(8, 28))):
        team = int(rng.integers(0, n_groups_spec + 2))  # some unmatched
        labels = (
            {"team": f"t{team}"} if team < n_groups_spec else {}
        )
        pods.append(
            _pod(f"p{p}", labels, cpu=str(int(rng.integers(1, 3))))
        )
    return pods, profiles, groups


def _encode(pods, profiles, groups):
    snap = snapshot_from_pods(pods)
    return encode_snapshot(snap, profiles, constraints=groups)


@pytest.fixture(autouse=True)
def _fresh_constraint_state():
    encoder_mod.reset_constraint_state()
    yield
    encoder_mod.reset_constraint_state()


# -- compiler units ----------------------------------------------------------


class TestSpec:
    def test_validation_rules(self):
        with pytest.raises(ValueError, match="requires a name"):
            ConstraintGroup(pod_selector={"a": "b"}, compact=True).validate()
        with pytest.raises(ValueError, match="podSelector"):
            ConstraintGroup(name="x", compact=True).validate()
        with pytest.raises(ValueError, match="declares no constraint"):
            ConstraintGroup(name="x", pod_selector={"a": "b"}).validate()
        with pytest.raises(ValueError, match="mutually exclusive"):
            ConstraintGroup(
                name="x", pod_selector={"a": "b"},
                anti_affinity=True, compact=True,
            ).validate()
        # any non-empty label key is a legal spread axis now
        SpreadSpec(topology_key="kubernetes.io/hostname").validate()
        with pytest.raises(ValueError, match="topologyKey"):
            SpreadSpec(topology_key="").validate()
        with pytest.raises(ValueError, match="maxSkew"):
            SpreadSpec(max_skew=0).validate()
        with pytest.raises(ValueError, match="single"):
            validate_constraints([
                ConstraintGroup(
                    name="x", pod_selector={"a": "b"},
                    spread=SpreadSpec(topology_key="rack"),
                ),
                ConstraintGroup(
                    name="y", pod_selector={"c": "d"},
                    spread=SpreadSpec(),
                ),
            ])
        with pytest.raises(ValueError, match="duplicate"):
            validate_constraints([
                ConstraintGroup(
                    name="x", pod_selector={"a": "b"}, compact=True
                ),
                ConstraintGroup(
                    name="x", pod_selector={"c": "d"}, compact=True
                ),
            ])

    def test_canonical_form_is_hashable_and_order_sensitive(self):
        g1 = ConstraintGroup(
            name="a", pod_selector={"k": "v"}, compact=True
        )
        g2 = ConstraintGroup(
            name="b", pod_selector={"k": "w"}, spread=SpreadSpec()
        )
        assert canonical_constraints([]) == ()
        assert hash(canonical_constraints([g1, g2])) != hash(
            canonical_constraints([g2, g1])
        )


class TestCompiler:
    def test_membership_first_match_wins(self):
        label_sets = [(), (("a", "1"),), (("a", "1"), ("b", "2"))]
        labels_id = np.array([0, 1, 2, 2], np.int32)
        groups = [
            ConstraintGroup(
                name="g0", pod_selector={"b": "2"}, compact=True
            ),
            ConstraintGroup(
                name="g1", pod_selector={"a": "1"}, compact=True
            ),
        ]
        m = compile_membership(label_sets, labels_id, groups)
        # set 1 matches only g1 (-> 2); set 2 matches g0 first (-> 1)
        assert m.tolist() == [0, 2, 1, 1]

    def test_meta_universes(self):
        profiles = [
            _profile(zone="z2"),
            _profile(zone="z1", reservation="silver"),
            _profile(),
        ]
        groups = [
            ConstraintGroup(
                name="s", pod_selector={"a": "1"}, spread=SpreadSpec()
            ),
            ConstraintGroup(
                name="r", pod_selector={"b": "1"}, reservation="gold"
            ),
            ConstraintGroup(
                name="c", pod_selector={"c": "1"}, compact=True
            ),
        ]
        meta = constraint_meta(groups, profiles)
        # spec claims UNION group reservation labels, sorted
        assert meta.reservations == ["gold", "silver"]
        assert meta.zones == ["z1", "z2"]
        assert meta.spread_names == ["s"]
        assert meta.compact_names == ["c"]

    def test_spread_split_balanced_caps_and_boundary_cuts(self):
        groups = [
            ConstraintGroup(
                name="s", pod_selector={"a": "1"}, spread=SpreadSpec()
            )
        ]
        profiles = [_profile(zone="z1"), _profile(zone="z2")]
        membership = np.array([1, 1, 0], np.int32)
        weights = np.array([5, 2, 3], np.int32)
        valid = np.array([True, True, True])
        compiled = compile_rows(
            membership, weights, valid, profiles, groups
        )
        # total member weight 7 over 2 zones -> caps [4, 3] (+ sink 0)
        np.testing.assert_array_equal(
            compiled.spread_cap, [[4, 3, 0]]
        )
        # row 0 (w=5) straddles the z1 quota boundary at rank 4: split
        # 4+1; row 1 fits inside z2's quota; row 2 passes through
        np.testing.assert_array_equal(
            compiled.rep, [0, 0, 1, 2]
        )
        np.testing.assert_array_equal(
            compiled.row_weight, [4, 1, 2, 3]
        )
        np.testing.assert_array_equal(
            compiled.spread_slot, [1, 1, 1, 0]
        )
        # weight is conserved per source row
        assert compiled.row_weight[:2].sum() == 5

    def test_inert_when_no_members_or_no_zones(self):
        groups = [
            ConstraintGroup(
                name="s", pod_selector={"a": "1"}, spread=SpreadSpec()
            )
        ]
        # members exist but no zoned profiles -> inert spread
        compiled = compile_rows(
            np.array([1], np.int32),
            np.array([3], np.int32),
            np.array([True]),
            [_profile()],
            groups,
        )
        assert compiled.spread_slot is None
        assert compiled.spread_cap is None
        np.testing.assert_array_equal(compiled.rep, [0])

    def test_reservation_operands_and_fencing_universe(self):
        groups = [
            ConstraintGroup(
                name="r", pod_selector={"t": "1"}, reservation="gold"
            )
        ]
        profiles = [_profile(reservation="gold"), _profile()]
        compiled = compile_rows(
            np.array([1, 0], np.int32),
            np.array([1, 1], np.int32),
            np.array([True, True]),
            profiles,
            groups,
        )
        np.testing.assert_array_equal(compiled.claim, [1, 0])
        np.testing.assert_array_equal(
            compiled.group_reservation, [1, 0]
        )

    def test_reserved_group_fences_even_without_claimants(self):
        """A karpenter.sh/reservation-labeled group joins the operand
        universe even when NO spec claims it — unclaimed pods must be
        fenced off reserved capacity."""
        groups = [
            ConstraintGroup(
                name="c", pod_selector={"t": "1"}, compact=True
            )
        ]
        profiles = [_profile(reservation="idle"), _profile()]
        compiled = compile_rows(
            np.array([1, 0], np.int32),
            np.array([1, 1], np.int32),
            np.array([True, True]),
            profiles,
            groups,
        )
        # nobody claims -> claim all zeros, but the reserved group is
        # still marked so the kernel fences unclaimed pods off it
        np.testing.assert_array_equal(compiled.claim, [0, 0])
        np.testing.assert_array_equal(
            compiled.group_reservation, [1, 0]
        )

    def test_zone_reservation_label_helpers(self):
        labels = {ZONE_LABEL: "z9", RESERVATION_LABEL: "gold"}
        assert zone_of(labels) == "z9"
        assert reservation_of(labels) == "gold"
        assert zone_of({}) == ""
        assert reservation_of({}) == ""


# -- kernel semantics --------------------------------------------------------


def _inputs_from_compiled(requests, alloc, compiled, weights=None):
    """Hand-assemble BinPackInputs from a CompiledConstraints the way
    the encoder does (unpadded: the kernel accepts any extents)."""
    import jax.numpy as jnp

    P = len(compiled.rep)
    T = len(alloc)
    base = dict(
        pod_requests=jnp.asarray(
            np.asarray(requests, np.float32)[compiled.rep]
        ),
        pod_valid=jnp.ones(P, bool),
        pod_intolerant=jnp.zeros((P, 4), bool),
        pod_required=jnp.zeros((P, 4), bool),
        group_allocatable=jnp.asarray(np.asarray(alloc, np.float32)),
        group_taints=jnp.zeros((T, 4), bool),
        group_labels=jnp.zeros((T, 4), bool),
        pod_weight=jnp.asarray(compiled.row_weight),
    )
    for name, value in (
        ("pod_claim", compiled.claim),
        ("group_reservation", compiled.group_reservation),
        ("pod_pack_class", compiled.pack_class),
        ("pod_spread_slot", compiled.spread_slot),
        ("group_domain", compiled.group_domain),
        ("spread_cap", compiled.spread_cap),
        ("pod_exclusive", compiled.exclusive),
    ):
        if value is not None:
            base[name] = jnp.asarray(value)
    return B.BinPackInputs(**base)


class TestKernelSemantics:
    def test_reservation_fences_both_ways(self):
        groups = [
            ConstraintGroup(
                name="r", pod_selector={"t": "1"}, reservation="gold"
            )
        ]
        profiles = [_profile(reservation="gold"), _profile()]
        compiled = compile_rows(
            np.array([1, 0], np.int32),
            np.array([1, 1], np.int32),
            np.array([True, True]),
            profiles,
            groups,
        )
        inputs = _inputs_from_compiled(
            [[1, 1], [1, 1]],
            [[8, 8], [8, 8]],
            compiled,
        )
        out = jax.device_get(B.binpack(inputs, buckets=8))
        # claimant -> reserved group 0; unclaimed -> fenced to group 1
        assert out.assigned.tolist() == [0, 1]

    def test_spread_balances_across_zones(self):
        groups = [
            ConstraintGroup(
                name="s", pod_selector={"t": "1"}, spread=SpreadSpec()
            )
        ]
        profiles = [_profile(zone="z1"), _profile(zone="z2")]
        membership = np.ones(4, np.int32)
        compiled = compile_rows(
            membership,
            np.ones(4, np.int32),
            np.ones(4, bool),
            profiles,
            groups,
        )
        inputs = _inputs_from_compiled(
            [[1, 1]] * 4, [[8, 8], [8, 8]], compiled
        )
        out = jax.device_get(B.binpack(inputs, buckets=8))
        # without spread every pod would land on group 0; with balanced
        # quotas the assignment splits 2/2
        assert out.assigned_count.tolist() == [2, 2]
        meta = compiled.meta
        assert spread_skew(inputs, out.assigned, meta) == {"s": 0}

    def test_spread_custom_key_parity_with_zone(self):
        # the balanced-spread pin, extended to an arbitrary topology
        # axis: the SAME fleet labeled on a custom key compiles to
        # byte-identical operands and the kernel balances identically
        rack = "example.com/rack"
        z_groups = [
            ConstraintGroup(
                name="s", pod_selector={"t": "1"}, spread=SpreadSpec()
            )
        ]
        r_groups = [
            ConstraintGroup(
                name="s",
                pod_selector={"t": "1"},
                spread=SpreadSpec(topology_key=rack),
            )
        ]
        validate_constraints(r_groups)
        z_profiles = [_profile(zone="z1"), _profile(zone="z2")]
        r_profiles = [
            (
                {"cpu": 8.0, "memory": 32.0, "pods": 32.0},
                {(rack, z)},
                set(),
            )
            for z in ("z1", "z2")
        ]
        membership = np.ones(4, np.int32)
        compiled = []
        for profiles, groups in (
            (z_profiles, z_groups),
            (r_profiles, r_groups),
        ):
            compiled.append(
                compile_rows(
                    membership,
                    np.ones(4, np.int32),
                    np.ones(4, bool),
                    profiles,
                    groups,
                )
            )
        a, b = compiled
        for name in (
            "rep",
            "row_weight",
            "spread_slot",
            "group_domain",
            "spread_cap",
        ):
            assert np.array_equal(getattr(a, name), getattr(b, name))
        assert b.meta.topology_key == rack
        assert b.meta.zones == ["z1", "z2"]
        inputs = _inputs_from_compiled(
            [[1, 1]] * 4, [[8, 8], [8, 8]], b
        )
        out = jax.device_get(B.binpack(inputs, buckets=8))
        assert out.assigned_count.tolist() == [2, 2]
        assert spread_skew(inputs, out.assigned, b.meta) == {"s": 0}

    def test_compact_members_never_share_nodes(self):
        groups = [
            ConstraintGroup(
                name="c", pod_selector={"t": "1"}, compact=True
            )
        ]
        profiles = [_profile()]
        # 2 compact members + 2 plain pods, all 1 cpu on an 8-cpu node:
        # unconstrained everything fits one node; compact isolation
        # needs a second node for the members
        compiled = compile_rows(
            np.array([1, 1, 0, 0], np.int32),
            np.ones(4, np.int32),
            np.ones(4, bool),
            profiles,
            groups,
        )
        inputs = _inputs_from_compiled(
            [[1, 1]] * 4, [[8.0, 8.0]], compiled
        )
        out = jax.device_get(B.binpack(inputs, buckets=8))
        assert out.nodes_needed.tolist() == [2]
        un = dataclasses.replace(inputs, pod_pack_class=None)
        assert jax.device_get(
            B.binpack(un, buckets=8)
        ).nodes_needed.tolist() == [1]

    def test_anti_affinity_members_take_whole_nodes(self):
        groups = [
            ConstraintGroup(
                name="a", pod_selector={"t": "1"}, anti_affinity=True
            )
        ]
        compiled = compile_rows(
            np.array([1, 1, 0], np.int32),
            np.ones(3, np.int32),
            np.ones(3, bool),
            [_profile()],
            groups,
        )
        inputs = _inputs_from_compiled(
            [[1, 1]] * 3, [[8.0, 8.0]], compiled
        )
        out = jax.device_get(B.binpack(inputs, buckets=8))
        # 2 exclusive nodes + 1 shared node
        assert out.nodes_needed.tolist() == [3]

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_xla_equals_numpy_bitwise(self, seed):
        rng = np.random.default_rng(seed)
        pods, profiles, groups = _random_world(rng)
        inputs = _encode(pods, profiles, groups)
        assert B.has_constraint_operands(inputs)
        out = jax.device_get(B.binpack(inputs, buckets=8))
        ref = binpack_numpy(inputs, buckets=8)
        np.testing.assert_array_equal(
            out.assigned, np.asarray(ref.assigned)
        )
        np.testing.assert_array_equal(
            out.assigned_count, np.asarray(ref.assigned_count)
        )
        np.testing.assert_array_equal(
            out.nodes_needed, np.asarray(ref.nodes_needed)
        )
        assert int(out.unschedulable) == int(ref.unschedulable)

    def test_constraint_mask_parity_jnp_np(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(7)
        P, T, S, D = 13, 5, 2, 3
        claim = rng.integers(0, 3, P).astype(np.int32)
        reservation = rng.integers(0, 3, T).astype(np.int32)
        slot = rng.integers(0, S + 1, P).astype(np.int32)
        domain = rng.integers(0, D + 1, T).astype(np.int32)
        caps = rng.integers(0, 6, (S, D + 1)).astype(np.int32)
        weight = rng.integers(1, 4, P).astype(np.int32)
        valid = rng.random(P) < 0.9
        got_np = B.constraint_mask(
            claim, reservation, slot, domain, caps, weight, valid,
            xp=np,
        )
        got_jnp = np.asarray(
            B.constraint_mask(
                jnp.asarray(claim), jnp.asarray(reservation),
                jnp.asarray(slot), jnp.asarray(domain),
                jnp.asarray(caps), jnp.asarray(weight),
                jnp.asarray(valid), xp=jnp,
            )
        )
        np.testing.assert_array_equal(got_np, got_jnp)
        # absent halves broadcast instead of materializing zeros
        np.testing.assert_array_equal(
            np.broadcast_to(
                B.constraint_mask(
                    claim, None, None, None, None, weight, valid, xp=np
                ),
                (P, T),
            ),
            np.broadcast_to((claim == 0)[:, None], (P, T)),
        )


# -- the seeded property pin -------------------------------------------------


class TestBatchedEqualsPerGroup:
    @pytest.mark.parametrize("seed", list(range(6)))
    def test_batched_verdicts_equal_independent_per_group_solves(
        self, seed
    ):
        """Per-pod verdicts of the ONE batched constrained dispatch ==
        solving each constraint group's members independently (the
        batched inputs with every other row invalidated). Spread ranks
        only accumulate over valid same-slot rows and every other
        operand is per-row, so the per-group solve is exact — lp_bound
        is excluded (an LP over a subset is not additive)."""
        import jax.numpy as jnp

        rng = np.random.default_rng(100 + seed)
        pods, profiles, groups = _random_world(rng)
        inputs = _encode(pods, profiles, groups)
        membership = _row_membership(inputs, pods, groups)
        batched = jax.device_get(B.binpack(inputs, buckets=8))
        for g in range(len(groups) + 1):  # 0 = the unconstrained rest
            rows = membership == g
            if not rows.any():
                continue
            solo_valid = np.asarray(inputs.pod_valid) & rows
            solo = dataclasses.replace(
                inputs,
                pod_valid=jnp.asarray(solo_valid),
                pod_weight=jnp.asarray(
                    np.where(rows, np.asarray(inputs.pod_weight), 0)
                    .astype(np.int32)
                ),
            )
            out = jax.device_get(B.binpack(solo, buckets=8))
            np.testing.assert_array_equal(
                out.assigned[rows],
                batched.assigned[rows],
                err_msg=f"seed {seed} group {g}",
            )


def _row_membership(inputs, pods, groups):
    """Recompute per-ROW membership of the encoded inputs by matching
    each row's claim/slot/class/exclusive signature back to its group —
    rows are post-dedup, so pod-level membership can't be indexed
    directly."""
    P = np.asarray(inputs.pod_valid).shape[0]
    membership = np.zeros(P, np.int32)
    # the encoder guarantees: row operands were gathered from compiled
    # membership; reconstruct via the operand signatures
    claim = (
        np.asarray(inputs.pod_claim)
        if inputs.pod_claim is not None
        else np.zeros(P, np.int32)
    )
    slot = (
        np.asarray(inputs.pod_spread_slot)
        if inputs.pod_spread_slot is not None
        else np.zeros(P, np.int32)
    )
    pc = (
        np.asarray(inputs.pod_pack_class)
        if inputs.pod_pack_class is not None
        else None
    )
    excl = (
        np.asarray(inputs.pod_exclusive)
        if inputs.pod_exclusive is not None
        else np.zeros(P, bool)
    )
    meta = constraint_meta(groups, [])
    for gidx, group in enumerate(groups):
        sig = np.ones(P, bool)
        if group.reservation:
            c = 1 + meta.reservations.index(group.reservation)
            sig &= claim == c
        elif group.spread is not None:
            s = 1 + meta.spread_names.index(group.name)
            sig &= slot == s
        elif group.compact:
            k = 1 + meta.compact_names.index(group.name)
            sig &= (
                pc[:, k]
                if pc is not None and k < pc.shape[1]
                else np.zeros(P, bool)
            )
        elif group.anti_affinity:
            sig &= excl
        membership[sig & (membership == 0)] = gidx + 1
    return membership


# -- wire compat -------------------------------------------------------------


def _assert_inputs_identical(a, b):
    for f in dataclasses.fields(a):
        va, vb = getattr(a, f.name), getattr(b, f.name)
        if va is None or vb is None:
            assert va is vb, f.name
        else:
            np.testing.assert_array_equal(
                np.asarray(va), np.asarray(vb), err_msg=f.name
            )


class TestWireCompat:
    def test_absent_constraints_encode_byte_identical(self):
        """No spec.constraints anywhere -> the six constraint operands
        stay None and every other operand is byte-identical to an
        encode that predates the constraint plane (constraints=None and
        constraints=[] take the same path)."""
        rng = np.random.default_rng(3)
        pods, profiles, _ = _random_world(rng)
        snap = snapshot_from_pods(pods)
        base = encode_snapshot(snap, profiles)
        for variant in (None, [], ()):
            got = encode_snapshot(
                snapshot_from_pods(pods), profiles, constraints=variant
            )
            for f in B._CONSTRAINT_FIELDS:
                assert getattr(got, f) is None, f
            _assert_inputs_identical(base, got)
        assert encoder_mod.constraint_stats["compiles"] == 0

    def test_nonmatching_constraints_keep_wire_unchanged(self):
        """In a fleet with NO reserved capacity, groups whose selectors
        match no pod attach nothing: the operands stay None and the
        arrays are byte-identical. (With reserved profiles present,
        admitting a constraint plane activates reservation fencing even
        without claimants — pinned in TestCompiler.)"""
        rng = np.random.default_rng(4)
        pods, profiles, _ = _random_world(rng)
        profiles = [
            p for p in profiles
            if not any(k == RESERVATION_LABEL for k, _ in p[1])
        ]
        groups = [
            ConstraintGroup(
                name="ghost",
                pod_selector={"no-such-label": "x"},
                spread=SpreadSpec(),
            )
        ]
        base = encode_snapshot(snapshot_from_pods(pods), profiles)
        got = encode_snapshot(
            snapshot_from_pods(pods), profiles, constraints=groups
        )
        for f in B._CONSTRAINT_FIELDS:
            assert getattr(got, f) is None, f
        _assert_inputs_identical(base, got)

    def test_membership_splits_dedup_of_identical_specs(self):
        """Two spec-identical pods in different groups must dedup apart
        (labels are not part of the unconstrained dedup identity)."""
        pods = [
            _pod("a", {"team": "t0"}),
            _pod("b", {"team": "t1"}),
        ]
        profiles = [_profile(reservation="gold"), _profile()]
        groups = [
            ConstraintGroup(
                name="g0", pod_selector={"team": "t0"},
                reservation="gold",
            ),
        ]
        un = encode_snapshot(snapshot_from_pods(pods), profiles)
        # unconstrained: one deduped row of weight 2
        assert int(np.asarray(un.pod_weight).sum()) == 2
        assert int((np.asarray(un.pod_weight) > 0).sum()) == 1
        con = encode_snapshot(
            snapshot_from_pods(pods), profiles, constraints=groups
        )
        live = np.asarray(con.pod_weight) > 0
        assert int(live.sum()) == 2  # membership split the row
        claims = np.asarray(con.pod_claim)[live]
        assert sorted(claims.tolist()) == [0, 1]


# -- pallas guard (third dispatch site) --------------------------------------


class TestPallasGuard:
    def test_fold_for_pallas_reroutes_constrained_inputs(self):
        rng = np.random.default_rng(11)
        pods, profiles, groups = _random_world(rng)
        inputs = _encode(pods, profiles, groups)
        assert B.has_constraint_operands(inputs)
        _, route = B._fold_for_pallas(inputs)
        assert route == "xla"

    def test_service_reroutes_and_counts(self):
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.solver import SolverService

        rng = np.random.default_rng(12)
        pods, profiles, groups = _random_world(rng)
        inputs = _encode(pods, profiles, groups)
        service = SolverService(
            registry=GaugeRegistry(), backend="pallas",
            health_failure_threshold=100,
        )
        try:
            out = service.solve(inputs, buckets=8)
            assert service.stats.constraint_reroutes >= 1
            ref = binpack_numpy(inputs, buckets=8)
            np.testing.assert_array_equal(
                np.asarray(out.assigned), np.asarray(ref.assigned)
            )
        finally:
            service.close()


# -- verdict helpers ---------------------------------------------------------


class TestVerdicts:
    def test_reservation_fill_counts_placed_claimants(self):
        groups = [
            ConstraintGroup(
                name="r", pod_selector={"t": "1"}, reservation="gold"
            )
        ]
        profiles = [_profile(reservation="gold")]
        compiled = compile_rows(
            np.array([1, 1], np.int32),
            np.array([1, 1], np.int32),
            np.array([True, True]),
            profiles,
            groups,
        )
        inputs = _inputs_from_compiled(
            [[1, 1], [99, 99]], [[8, 8]], compiled
        )
        out = jax.device_get(B.binpack(inputs, buckets=8))
        fill = reservation_fill(inputs, out.assigned, compiled.meta)
        assert fill == {"gold": 0.5}  # one of two claimants placed

    def test_idle_reservation_reports_full(self):
        meta = constraint_meta(
            [
                ConstraintGroup(
                    name="r", pod_selector={"t": "1"},
                    reservation="gold",
                )
            ],
            [],
        )
        inputs = B.BinPackInputs(
            pod_requests=np.zeros((1, 2), np.float32),
            pod_valid=np.zeros(1, bool),
            pod_intolerant=np.zeros((1, 1), bool),
            pod_required=np.zeros((1, 1), bool),
            group_allocatable=np.zeros((1, 2), np.float32),
            group_taints=np.zeros((1, 1), bool),
            group_labels=np.zeros((1, 1), bool),
        )
        assert reservation_fill(
            inputs, np.array([-1]), meta
        ) == {"gold": 1.0}


class TestRegressionGuard:
    def test_batched_constrained_beats_per_group_loop(self):
        """Non-slow guard for the bench-constraints claim: ONE batched
        masked-operand dispatch must beat the per-group sequential loop
        (generously — the published numbers live in
        docs/BENCHMARKS.md / BASELINE.json)."""
        import time

        import jax.numpy as jnp

        rng = np.random.default_rng(5)
        pods, profiles, groups = _random_world(rng, n_groups_spec=3)
        pods = pods * 12  # enough work for a stable timing signal
        for i, p in enumerate(pods):
            p.metadata.name = f"p{i}"
        inputs = _encode(pods, profiles, groups)
        membership = _row_membership(inputs, pods, groups)
        solos = []
        for g in range(len(groups) + 1):
            rows = membership == g
            solos.append(dataclasses.replace(
                inputs,
                pod_valid=jnp.asarray(
                    np.asarray(inputs.pod_valid) & rows
                ),
                pod_weight=jnp.asarray(np.where(
                    rows, np.asarray(inputs.pod_weight), 0
                ).astype(np.int32)),
            ))
        # warm both programs (same shapes: the solos share one compile)
        jax.block_until_ready(B.binpack(inputs, buckets=8))
        jax.block_until_ready(B.binpack(solos[0], buckets=8))

        def best_of(fn, reps=3):
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
            return min(times)

        batched = best_of(
            lambda: jax.block_until_ready(B.binpack(inputs, buckets=8))
        )
        sequential = best_of(lambda: [
            jax.block_until_ready(B.binpack(s, buckets=8))
            for s in solos
        ])
        assert batched < sequential, (
            f"batched {batched * 1e3:.2f}ms not faster than the "
            f"per-group loop {sequential * 1e3:.2f}ms"
        )
