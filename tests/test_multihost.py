"""Multi-host initialization seam (parallel/multihost.py).

jax.distributed.initialize is once-per-process, so the live join runs in
a SUBPROCESS: a 1-process CPU "fleet" joins itself as coordinator,
builds the mesh over its global devices, and runs the certified sharded
program — proving the deployment path (initialize -> build_mesh ->
fleet step) composes, without multi-host hardware.
"""

from __future__ import annotations

import os
import subprocess
import sys


def test_single_process_fleet_joins_and_solves():
    script = r"""
from karpenter_tpu.utils.backend import force_virtual_cpu
force_virtual_cpu(4)  # the one owner of the XLA_FLAGS/platform sequence
import jax
from karpenter_tpu.parallel.multihost import initialize_multihost
joined = initialize_multihost(
    coordinator_address="localhost:12399", num_processes=1, process_id=0
)
assert joined, "explicit 1-process topology must join"
assert jax.process_count() == 1
assert jax.device_count() >= 4
# idempotent
assert initialize_multihost() is True
from karpenter_tpu.parallel.mesh import dryrun_fleet_step
dryrun_fleet_step(jax.device_count())
print("MULTIHOST-OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "MULTIHOST-OK" in proc.stdout


def test_no_topology_is_single_host_noop():
    """Without a coordinator/env topology on a non-TPU host, the seam
    reports False and the caller proceeds single-host. Runs in a fresh
    subprocess: the join must precede backend initialization, and the
    pytest process has long initialized its virtual mesh."""
    script = r"""
from karpenter_tpu.parallel.multihost import initialize_multihost
assert initialize_multihost() is False
print("NOOP-OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        env.pop(var, None)
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "NOOP-OK" in proc.stdout


def test_join_after_backend_init_raises_loudly():
    """Calling the seam after XLA initialized (a caller ordering bug)
    must raise, never be classified as 'no topology'."""
    import jax
    import pytest

    from karpenter_tpu.parallel import multihost

    jax.devices()  # deterministically initialize the in-process backend
    multihost._initialized = False
    with pytest.raises(RuntimeError, match="before"):
        multihost.initialize_multihost()


def test_partial_topology_raises(monkeypatch):
    """A half-configured host must crash loudly, never serve single-host
    while the rest of the fleet hangs waiting for it."""
    import importlib

    import pytest

    from karpenter_tpu.parallel import multihost

    importlib.reload(multihost)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    with pytest.raises(ValueError, match="partial multihost topology"):
        multihost.initialize_multihost()
