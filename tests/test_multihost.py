"""Multi-host initialization seam (parallel/multihost.py).

jax.distributed.initialize is once-per-process, so the live join runs in
a SUBPROCESS: a 1-process CPU "fleet" joins itself as coordinator,
builds the mesh over its global devices, and runs the certified sharded
program — proving the deployment path (initialize -> build_mesh ->
fleet step) composes, without multi-host hardware.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest


def _clean_cpu_env() -> dict:
    """Subprocess env forcing the CPU backend with no inherited
    multihost topology (the pytest process's axon/topology vars must
    not leak into the spawned fleet)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        env.pop(var, None)
    return env


def test_single_process_fleet_joins_and_solves():
    script = r"""
from karpenter_tpu.utils.backend import force_virtual_cpu
force_virtual_cpu(4)  # the one owner of the XLA_FLAGS/platform sequence
import jax
from karpenter_tpu.parallel.multihost import initialize_multihost
joined = initialize_multihost(
    coordinator_address="localhost:12399", num_processes=1, process_id=0
)
assert joined, "explicit 1-process topology must join"
assert jax.process_count() == 1
assert jax.device_count() >= 4
# idempotent
assert initialize_multihost() is True
from karpenter_tpu.parallel.mesh import dryrun_fleet_step
dryrun_fleet_step(jax.device_count())
print("MULTIHOST-OK")
"""
    env = _clean_cpu_env()
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "MULTIHOST-OK" in proc.stdout


_TWO_PROCESS_SCRIPT = r"""
import sys
pid = int(sys.argv[1]); port = sys.argv[2]
from karpenter_tpu.utils.backend import force_virtual_cpu
force_virtual_cpu(4)  # 4 local devices per process -> 8 global
from karpenter_tpu.parallel.multihost import initialize_multihost
joined = initialize_multihost(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
)
assert joined, "explicit 2-process topology must join"
import jax
import numpy as np
assert jax.process_count() == 2, jax.process_count()
# the conftest env may pre-force a LARGER per-process device count (the
# flag is never reduced); the invariant is the split, not the total
n_local = len(jax.local_devices())
assert n_local >= 4
assert jax.device_count() == 2 * n_local, (jax.device_count(), n_local)

import dataclasses
import jax.numpy as jnp
from karpenter_tpu.parallel.mesh import (
    build_mesh, example_binpack_inputs, example_decision_inputs,
    fleet_step, shard_binpack_inputs, shard_decision_inputs,
)

rng = np.random.default_rng(7)
weights = np.ones(33, np.int32); weights[:4] = 5
d_in = example_decision_inputs(N=16, M=4)
b_in = dataclasses.replace(
    example_binpack_inputs(P_=33, T=8, K=8, L=8),
    pod_weight=jnp.asarray(weights),
    pod_group_forbidden=jnp.asarray(rng.random((33, 8)) < 0.3),
    pod_group_score=jnp.asarray(rng.integers(0, 100, (33, 8)).astype(np.float32)),
    pod_exclusive=jnp.asarray(rng.random(33) < 0.25),
)
# the GLOBAL slice x pods x groups mesh spans both processes
mesh = build_mesh(n_devices=jax.device_count(), slices=2)

# single-process reference on LOCAL devices over the SAME mesh-padded
# inputs (identical on both processes by construction: same seeds), so
# shard indices line up with the padded global shape
from karpenter_tpu.parallel.mesh import (
    pad_binpack_inputs_for_mesh, pad_decision_inputs_for_mesh,
)
pb_in = pad_binpack_inputs_for_mesh(b_in, mesh)
pd_in = pad_decision_inputs_for_mesh(d_in, mesh)
d_ref, b_ref = jax.device_get(fleet_step(pd_in, pb_in, buckets=8))
gd_in = shard_decision_inputs(mesh, d_in)
gb_in = shard_binpack_inputs(mesh, b_in)
d_out, b_out = fleet_step(gd_in, gb_in, buckets=8)

def check(global_arr, ref):
    for shard in global_arr.addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), np.asarray(ref[shard.index])
        )

check(b_out.assigned, np.asarray(b_ref.assigned))       # includes mesh padding rows
check(d_out.desired, np.asarray(d_ref.desired))
check(b_out.nodes_needed, np.asarray(b_ref.nodes_needed))
check(b_out.assigned_count, np.asarray(b_ref.assigned_count))
print(f"TWOPROC-OK pid={pid}")
"""


def _cpu_multiprocess_supported() -> bool:
    """jax <= 0.4.x cannot run MULTIPROCESS computations on the CPU
    backend: the two-process fleet_step (and even the device_put of a
    cross-process sharding, which asserts equality via a collective)
    fails with XlaRuntimeError 'Multiprocess computations aren't
    implemented on the CPU backend'. Cross-process CPU collectives need
    the gloo-backed support of later jax releases, so the two-process
    parity test is version-gated rather than deleted — it self-arms when
    the image's jax can run it."""
    import jax

    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return True  # unknown scheme: let the test speak for itself
    return (major, minor) >= (0, 5)


@pytest.mark.skipif(
    not _cpu_multiprocess_supported(),
    reason="jax CPU backend cannot run multiprocess computations before "
    "0.5 ('Multiprocess computations aren't implemented on the CPU "
    "backend'); the 2-process fleet parity check needs cross-process "
    "CPU collectives",
)
def test_two_process_fleet_joins_and_matches_single_process():
    """THE multi-host seam, exercised with two real processes
    (coordinator + worker) on the CPU backend: both join via
    jax.distributed, build one GLOBAL 2-slice mesh over 8 devices split
    4+4 across the processes, run the collective fleet_step, and every
    addressable output shard equals the single-process reference
    (r3 verdict item 5 — the one seam the single-process dryrun cannot
    prove)."""
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = _clean_cpu_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _TWO_PROCESS_SCRIPT, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for proc in procs:
            out, err = proc.communicate(timeout=420)
            outs.append((proc.returncode, out, err))
    finally:
        for proc in procs:
            if proc.poll() is None:
                # kill then reap: drain the pipes so a hung join still
                # leaves its stderr for diagnosis, and no zombie
                # survives into the rest of the pytest run
                proc.kill()
                out, err = proc.communicate()
                print(f"killed pid={proc.pid} stderr tail:\n{err[-2000:]}")
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"process {pid} failed:\n{err[-3000:]}"
        assert f"TWOPROC-OK pid={pid}" in out
    # padding rows equal too: the check covered the full padded arrays


def test_two_sidecar_fleet_joins_and_serves():
    """The DEPLOYMENT contract (docs/OPERATIONS.md 'Scaling past one
    chip'): one solver sidecar per host, `--multihost`, topology from
    the standard env. Two real `python -m karpenter_tpu.sidecar`
    processes join one jax.distributed fleet; the coordinator's Health
    reports the GLOBAL device count (both processes' devices) and its
    Solve RPC answers identically to an in-process solve."""
    import json
    import socket

    import numpy as np

    ports = []
    for _ in range(3):  # coordinator + two gRPC ports
        with socket.socket() as s:
            s.bind(("localhost", 0))
            ports.append(s.getsockname()[1])
    coord, grpc0, grpc1 = ports
    procs = []
    try:
        for pid, gport in ((0, grpc0), (1, grpc1)):
            env = _clean_cpu_env()
            # pin the per-process device count so the global-count
            # assertion below can DISTINGUISH a joined fleet (8) from a
            # lone sidecar that failed to join (4)
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
            env["JAX_COORDINATOR_ADDRESS"] = f"localhost:{coord}"
            env["JAX_NUM_PROCESSES"] = "2"
            env["JAX_PROCESS_ID"] = str(pid)
            procs.append(
                subprocess.Popen(
                    [
                        # -u: the banner must not sit in a block buffer
                        sys.executable, "-u", "-m",
                        "karpenter_tpu.sidecar",
                        "--multihost", "--host", "127.0.0.1",
                        "--port", str(gport),
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=env,
                )
            )
        # wait for the coordinator's serving banner: select-based so a
        # stuck coordinator trips the deadline and a crashed one fails
        # fast with its stderr (drained in the finally block)
        import select
        import time

        deadline = time.monotonic() + 120
        banner = None
        while time.monotonic() < deadline and banner is None:
            if procs[0].poll() is not None:
                break  # coordinator died; finally drains its stderr
            ready, _, _ = select.select([procs[0].stdout], [], [], 0.5)
            if ready:
                line = procs[0].stdout.readline()
                if line:
                    banner = json.loads(line)
        assert banner and banner["serving"].endswith(str(grpc0)), (
            f"no serving banner (coordinator rc={procs[0].poll()})"
        )

        from karpenter_tpu.ops.binpack import solve
        from karpenter_tpu.parallel.mesh import example_binpack_inputs
        from karpenter_tpu.sidecar.client import SolverClient

        client = SolverClient(f"127.0.0.1:{grpc0}", timeout_seconds=60.0)
        ok, health = client.health()
        assert ok
        # the coordinator sees the GLOBAL device set (4 local + 4 from
        # the worker); a lone sidecar that failed to join would see 4
        assert health["device_count"] == 8, health

        inputs = example_binpack_inputs(P_=64, T=8)
        remote = client.solve(inputs, buckets=8)
        local = solve(inputs, buckets=8)
        np.testing.assert_array_equal(
            np.asarray(remote.assigned), np.asarray(local.assigned)
        )
        assert int(remote.unschedulable) == int(local.unschedulable)
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
            # drain ALIVE and DEAD alike: a crashed sidecar's stderr is
            # the diagnostic for why the banner never came
            _out, err = proc.communicate()
            tail = err[-1500:] if err else ""
            print(f"sidecar pid={proc.pid} rc={proc.returncode} "
                  f"stderr tail:\n{tail}")


def test_no_topology_is_single_host_noop():
    """Without a coordinator/env topology on a non-TPU host, the seam
    reports False and the caller proceeds single-host. Runs in a fresh
    subprocess: the join must precede backend initialization, and the
    pytest process has long initialized its virtual mesh."""
    script = r"""
from karpenter_tpu.parallel.multihost import initialize_multihost
assert initialize_multihost() is False
print("NOOP-OK")
"""
    env = _clean_cpu_env()
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "NOOP-OK" in proc.stdout


def test_join_after_backend_init_raises_loudly():
    """Calling the seam after XLA initialized (a caller ordering bug)
    must raise, never be classified as 'no topology'."""
    import jax
    import pytest

    from karpenter_tpu.parallel import multihost

    jax.devices()  # deterministically initialize the in-process backend
    multihost._initialized = False
    with pytest.raises(RuntimeError, match="before"):
        multihost.initialize_multihost()


def test_partial_topology_raises(monkeypatch):
    """A half-configured host must crash loudly, never serve single-host
    while the rest of the fleet hangs waiting for it."""
    import importlib

    import pytest

    from karpenter_tpu.parallel import multihost

    importlib.reload(multihost)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    with pytest.raises(ValueError, match="partial multihost topology"):
        multihost.initialize_multihost()
