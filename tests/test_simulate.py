"""What-if simulation API: per-row assignments surfaced from the solve
the production tick already runs, plus hypothetical-group deltas.

reference anchor: no reference analog (the producer is stubbed there);
intent is DESIGN.md 'Pending Pods' — show the placement the signal
promises, without mutating anything.
"""

import json

import pytest

from karpenter_tpu.simulate import simulate, simulate_delta
from karpenter_tpu.store.store import Store

from tests.test_pendingcapacity import pending_mp, pending_pod, ready_node


@pytest.fixture
def cluster():
    store = Store()
    store.create(ready_node("n-a", {"group": "a"}, cpu="4", memory="8Gi"))
    store.create(pending_mp("group-a", {"group": "a"}))
    return store


class TestSimulate:
    def test_rows_map_back_to_pods(self, cluster):
        for i in range(3):
            cluster.create(pending_pod(f"small-{i}", cpu="1", memory="1Gi"))
        cluster.create(pending_pod("huge", cpu="64", memory="1Gi"))
        report = simulate(cluster)

        assert report["groups"]["default/group-a"]["pending_pods"] == 3
        assert not report["groups"]["default/group-a"]["what_if"]
        assert report["unschedulable_pods"] == 1
        by_pod = {row["pod"]: row for row in report["rows"]}
        # the 3 identical pods dedup into one row under a representative
        small_rows = [
            r for r in report["rows"]
            if r["pod"].startswith("default/small")
        ]
        assert len(small_rows) == 1 and small_rows[0]["pods"] == 3
        assert small_rows[0]["assigned"] == "default/group-a"
        assert by_pod["default/huge"]["assigned"] is None

    def test_simulation_mutates_nothing(self, cluster):
        cluster.create(pending_pod("p", cpu="1", memory="1Gi"))
        before = cluster.get("MetricsProducer", "default", "group-a")
        simulate(cluster)
        after = cluster.get("MetricsProducer", "default", "group-a")
        assert after.metadata.resource_version == before.metadata.resource_version
        assert after.status.pending_capacity is None

    def test_what_if_group_absorbs_only_unserved_pods(self, cluster):
        """Hypothetical groups are appended last: first-feasible keeps
        pods on real groups, the what-if group only shows the capacity
        the fleet genuinely lacks."""
        for i in range(2):
            cluster.create(pending_pod(f"small-{i}", cpu="1", memory="1Gi"))
        cluster.create(pending_pod("huge", cpu="64", memory="64Gi"))
        report = simulate(
            cluster,
            what_if_groups=[
                {
                    "name": "metal",
                    "allocatable": {
                        "cpu": "96", "memory": "128Gi", "pods": "110",
                    },
                }
            ],
        )
        assert report["groups"]["default/group-a"]["pending_pods"] == 2
        assert report["groups"]["metal"]["what_if"]
        assert report["groups"]["metal"]["pending_pods"] == 1
        assert report["groups"]["metal"]["additional_nodes_needed"] == 1
        assert report["unschedulable_pods"] == 0

    def test_what_if_respects_taints_and_labels(self, cluster):
        cluster.create(
            pending_pod("picky", cpu="1", node_selector={"disk": "ssd"})
        )
        no_label = simulate(
            cluster,
            what_if_groups=[
                {"name": "plain", "allocatable": {
                    "cpu": "8", "memory": "16Gi", "pods": "64"}}
            ],
        )
        assert no_label["unschedulable_pods"] == 1
        labeled = simulate(
            cluster,
            what_if_groups=[
                {
                    "name": "ssd",
                    "allocatable": {
                        "cpu": "8", "memory": "16Gi", "pods": "64",
                    },
                    "labels": {"disk": "ssd"},
                }
            ],
        )
        assert labeled["groups"]["ssd"]["pending_pods"] == 1
        tainted = simulate(
            cluster,
            what_if_groups=[
                {
                    "name": "ssd-tainted",
                    "allocatable": {
                        "cpu": "8", "memory": "16Gi", "pods": "64",
                    },
                    "labels": {"disk": "ssd"},
                    "taints": [
                        {"key": "d", "value": "x", "effect": "NoSchedule"}
                    ],
                }
            ],
        )
        assert tainted["unschedulable_pods"] == 1

    def test_delta_report(self, cluster):
        cluster.create(pending_pod("huge", cpu="64", memory="64Gi"))
        report = simulate_delta(
            cluster,
            [{"name": "metal", "allocatable": {
                "cpu": "96", "memory": "128Gi", "pods": "110"}}],
        )
        assert report["baseline"]["unschedulable_pods"] == 1
        assert report["what_if"]["unschedulable_pods"] == 0
        assert report["delta"]["unschedulable_pods"] == -1
        assert report["delta"]["groups"]["metal"] == {
            "pending_pods": 1,
            "additional_nodes_needed": 1,
        }

    def test_empty_pending_set(self, cluster):
        report = simulate(cluster)
        assert report["rows"] == []
        assert report["unschedulable_pods"] == 0
        assert report["groups"]["default/group-a"]["pending_pods"] == 0


class TestSimulateCLI:
    def test_cli_simulate_with_what_if(self, tmp_path, capsys):
        """`python -m karpenter_tpu --simulate` end to end over a WAL
        store, the documented operator workflow (OPERATIONS.md)."""
        from karpenter_tpu.__main__ import main
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        data_dir = str(tmp_path / "state")
        seed = KarpenterRuntime(Options(data_dir=data_dir))
        seed.store.create(ready_node("n-a", {"group": "a"}, cpu="4"))
        seed.store.create(pending_mp("group-a", {"group": "a"}))
        seed.store.create(pending_pod("huge", cpu="64", memory="64Gi"))
        seed.close()

        what_if = tmp_path / "what-if.json"
        what_if.write_text(json.dumps([
            {"name": "metal",
             "allocatable": {"cpu": "96", "memory": "128Gi", "pods": "110"}}
        ]))
        rc = main([
            "--simulate", "--what-if", str(what_if),
            "--data-dir", data_dir, "--no-leader-elect",
        ])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["delta"]["unschedulable_pods"] == -1
        assert report["what_if"]["groups"]["metal"]["what_if"]

    def test_cli_rejects_non_list_what_if(self, tmp_path, capsys):
        from karpenter_tpu.__main__ import main

        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"name": "not-a-list"}))
        rc = main([
            "--simulate", "--what-if", str(bad),
            "--data-dir", str(tmp_path / "s"), "--no-leader-elect",
        ])
        assert rc == 2


class TestSimulateFidelity:
    def test_pods_resource_defaults_like_live_profiles(self, cluster):
        """A what-if spec declaring only cpu/memory must not be silently
        infeasible: the pods resource defaults exactly as it does for
        live-node profiles and provider templates."""
        cluster.create(pending_pod("huge", cpu="64", memory="64Gi"))
        report = simulate(
            cluster,
            what_if_groups=[
                {"name": "metal",
                 "allocatable": {"cpu": "96", "memory": "128Gi"}}
            ],
        )
        assert report["groups"]["metal"]["pending_pods"] == 1
        assert report["unschedulable_pods"] == 0

    def test_cloud_api_taint_dialect_constrains(self, cluster):
        """NO_SCHEDULE (the GKE/EKS enum spelling) must constrain like
        NoSchedule — specs are declared like provider raw templates."""
        cluster.create(pending_pod("huge", cpu="64", memory="64Gi"))
        report = simulate(
            cluster,
            what_if_groups=[
                {
                    "name": "metal",
                    "allocatable": {"cpu": "96", "memory": "128Gi"},
                    "taints": [
                        {"key": "d", "value": "x", "effect": "NO_SCHEDULE"}
                    ],
                }
            ],
        )
        assert report["unschedulable_pods"] == 1

    def test_scale_from_zero_groups_use_template_resolver(self):
        """An empty group with a nodeGroupRef resolves its declared shape
        through the same seam the production solve uses, keeping the
        baseline honest."""
        store = Store()
        mp = pending_mp("empty-group", {"group": "zero"})
        mp.spec.pending_capacity.node_group_ref = "pool"
        store.create(mp)
        store.create(pending_pod("p", cpu="1", memory="1Gi"))

        def resolver(namespace, ref):
            assert (namespace, ref) == ("default", "pool")
            return (
                {"cpu": 8.0, "memory": 2**34, "pods": 110.0},
                {("group", "zero")},
                set(),
            )

        report = simulate(store, template_resolver=resolver)
        assert report["groups"]["default/empty-group"]["pending_pods"] == 1
        assert report["unschedulable_pods"] == 0

    def test_poisoned_producer_is_row_isolated(self, cluster):
        """One producer with a selector that blows up profile computation
        reports an error on its own group; the rest still solve."""
        bad = pending_mp("poisoned", {"group": "x"})
        bad.spec.pending_capacity.node_selector = None  # blows up matching
        cluster.create(bad)
        cluster.create(pending_pod("p", cpu="1", memory="1Gi"))
        report = simulate(cluster)
        assert "error" in report["groups"]["default/poisoned"]
        assert report["groups"]["default/poisoned"]["pending_pods"] == 0
        assert report["groups"]["default/group-a"]["pending_pods"] == 1

    def test_rows_are_namespace_qualified(self, cluster):
        cluster.create(pending_pod("p", cpu="1", memory="1Gi"))
        report = simulate(cluster)
        assert report["rows"][0]["pod"] == "default/p"

    def test_empty_what_if_list_still_yields_delta_shape(
        self, tmp_path, capsys
    ):
        """--what-if pointing at [] must produce the documented
        baseline/what_if/delta report, not the plain one."""
        from karpenter_tpu.__main__ import main
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        data_dir = str(tmp_path / "state")
        seed = KarpenterRuntime(Options(data_dir=data_dir))
        seed.store.create(pending_mp("group-a", {"group": "a"}))
        seed.close()
        empty = tmp_path / "none.json"
        empty.write_text("[]")
        rc = main([
            "--simulate", "--what-if", str(empty),
            "--data-dir", data_dir, "--no-leader-elect",
        ])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert set(report) == {"baseline", "what_if", "delta"}

    def test_preferred_affinity_cannot_steal_into_what_if(self, cluster):
        """The solver steers by preference score among feasible groups; a
        what-if group matching a pod's preference must NOT attract a pod
        a real group serves — score columns of hypothetical groups are
        zeroed, preserving the delta's 'genuinely lacking' meaning."""
        from karpenter_tpu.api.core import (
            Affinity,
            NodeAffinity,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            PreferredSchedulingTerm,
        )

        pod = pending_pod("prefers-ssd", cpu="1", memory="1Gi")
        pod.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred_during_scheduling_ignored_during_execution=[
                    PreferredSchedulingTerm(
                        weight=100,
                        preference=NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(
                                    key="disk", operator="In",
                                    values=["ssd"],
                                )
                            ]
                        ),
                    )
                ]
            )
        )
        cluster.create(pod)
        report = simulate(
            cluster,
            what_if_groups=[
                {
                    "name": "ssd-pool",
                    "allocatable": {"cpu": "8", "memory": "16Gi"},
                    "labels": {"disk": "ssd"},
                }
            ],
        )
        assert report["groups"]["default/group-a"]["pending_pods"] == 1
        assert report["groups"]["ssd-pool"]["pending_pods"] == 0

    def test_what_if_name_collision_is_uniquified(self, cluster):
        cluster.create(pending_pod("p", cpu="1", memory="1Gi"))
        report = simulate(
            cluster,
            what_if_groups=[
                {"name": "metal", "allocatable": {"cpu": "8", "memory": "8Gi"}},
                {"name": "metal", "allocatable": {"cpu": "8", "memory": "8Gi"}},
            ],
        )
        assert "metal" in report["groups"]
        assert "metal#2" in report["groups"]


class TestSimulatePreempt:
    """--simulate --preempt: the seeded spot-reclaim storm replay
    (docs/preemption.md). Deterministic under a fixed seed, so the
    regression pins exact counts, and mutation-free toward any caller
    state (the replay owns its store)."""

    def test_storm_replay_is_deterministic_and_preempts(self):
        from karpenter_tpu.simulate import simulate_preempt

        kwargs = dict(
            on_demand_nodes=2, spot_nodes=4, node_cpu=4.0,
            ticks=12, reclaim_tick=2, provision_lag=3, seed=7,
        )
        report = simulate_preempt(**kwargs)
        again = simulate_preempt(**kwargs)
        assert report == again, "seeded replay must be deterministic"

        # the storm actually displaced work and the engine actually
        # planned evictions through the service
        assert report["evictions_total"] >= 1
        assert report["preempt_dispatches"] >= 1
        assert report["scale_ups_total"] >= 1
        # the fleet recovered: services first, everything eventually
        assert report["service_recovery_tick"] is not None
        assert report["full_recovery_tick"] is not None
        assert (
            report["service_recovery_tick"]
            <= report["full_recovery_tick"]
        )
        # high-priority pods drained ahead of (or with) the general
        # pending set on every tick after the reclaim
        for tick in report["ticks"]:
            assert (
                tick["pending_high_priority"] <= tick["pending"]
            )

    def test_report_shape(self):
        from karpenter_tpu.simulate import simulate_preempt

        report = simulate_preempt(
            on_demand_nodes=2, spot_nodes=2, node_cpu=4.0,
            ticks=6, reclaim_tick=1, seed=0,
        )
        assert set(report) >= {
            "config", "ticks", "evictions_total", "scale_ups_total",
            "service_recovery_tick", "full_recovery_tick",
            "preempt_dispatches",
        }
        assert len(report["ticks"]) == 6


class TestSimulateRestartStorm:
    def test_storm_pins_crash_safety_contract(self, tmp_path):
        """The --simulate --restart-storm replay: exactly-once cloud
        actuation across every incarnation, FSM resumption (no
        re-cordon of a restored drain), a fence generation per boot,
        and the stale-incarnation replay probe REJECTED."""
        from karpenter_tpu.simulate import simulate_restart_storm

        report = simulate_restart_storm(
            nodes=4, crashes=2, seed=0, journal_dir=str(tmp_path)
        )
        assert report["restarts"] == 3
        assert report["duplicate_actuations"] == 0
        assert report["fence_rejections"] == 1
        assert report["stale_replay_applied"] is False
        assert report["resumed_not_recordoned"] is True
        assert report["fence_generation"] == 4  # one per boot + probe
        assert report["drains_completed"] == 3  # every empty node gone

    def test_storm_is_deterministic(self, tmp_path):
        from karpenter_tpu.simulate import simulate_restart_storm

        def run(sub):
            report = simulate_restart_storm(
                nodes=3, crashes=1, seed=7,
                journal_dir=str(tmp_path / sub),
            )
            report.pop("nodes_remaining")
            return report

        assert run("a") == run("b")


class TestSimulateEventloop:
    """ISSUE 14 acceptance (non-slow regression guard): the seeded
    pod-arrival replay must show tick-paced e2e p99 at interval scale
    (multi-second), event-driven e2e p99 SUB-SECOND on the same
    karpenter_reconcile_e2e_seconds histogram, the same fleet fixed
    point in both arms, and churn-storm solve amplification <= 2x —
    the `make bench-eventloop` contract at a fast scale."""

    CONFIG = dict(ticks=12, arrivals=10, storm_events=200, seed=7)

    def test_event_driven_is_sub_second_with_bounded_amplification(self):
        from karpenter_tpu.simulate import simulate_eventloop

        report = simulate_eventloop(**self.CONFIG)
        assert report["fixed_point_match"], (
            "event-driven and tick-paced arms must converge to the "
            "same fleet"
        )
        tick = report["tick_paced"]["e2e_seconds"]
        event = report["event_driven"]["e2e_seconds"]
        assert tick["n"] >= 1 and event["n"] >= 1
        assert tick["p99_s"] > 1.0, (
            "tick pacing must dominate the tick-paced arm's lead time"
        )
        assert event["p99_s"] < 1.0, (
            f"event passes must deliver sub-second e2e p99, got "
            f"{event['p99_s']}s"
        )
        storm = report["event_driven"]["storm"]
        assert storm["amplification"] <= 2.0, (
            f"churn-storm solve amplification must stay bounded: "
            f"{storm}"
        )
        assert storm["passes"] <= 4, (
            f"{storm['events']} events in one debounce window must "
            f"coalesce into a handful of passes, got {storm['passes']}"
        )

    def test_eventloop_replay_is_deterministic(self):
        """Scripted clock + manual passes + seeded arrivals: the whole
        report (latencies included) is a pure function of the seed."""
        from karpenter_tpu.simulate import simulate_eventloop

        assert (
            simulate_eventloop(**self.CONFIG)
            == simulate_eventloop(**self.CONFIG)
        )


class TestSimulateCost:
    """Satellite pin (docs/cost.md "Dry-running"): the --simulate --cost
    warm-pool replay must show a MEASURED provisioning lead-time
    reduction at equal-or-lower SLO-violation count — the acceptance
    headline — and the deterministic halves of the report must replay
    identically (the e2e histogram carries real wall time and is pinned
    by shape only)."""

    CONFIG = dict(
        ticks=60, ramp_start=15, ramp_ticks=10, spot_step_tick=40,
        provision_lag=4, min_samples=3, seed=7,
    )

    def _deterministic_view(self, report):
        view = {
            k: report[k]
            for k in ("config", "hourly_cost", "slo_violations",
                      "provisioning_lead")
        }
        view["provisioned"] = {
            run: report["runs"][run]["provisioned"]
            for run in ("warm_on", "warm_off")
        }
        return view

    def test_warm_pool_cuts_provisioning_lead_within_slo(self):
        from karpenter_tpu.simulate import simulate_cost

        report = simulate_cost(**self.CONFIG)
        lead = report["provisioning_lead"]
        assert lead["reduction_ticks"] > 0, (
            "warm pool must reduce the mean capacity-coverage lag"
        )
        assert lead["warm_on_mean_lag_ticks"] < lead[
            "warm_off_mean_lag_ticks"
        ]
        viol = report["slo_violations"]
        assert viol["warm_on"] <= viol["warm_off"]
        assert (
            viol["warm_on_shortfall_replica_ticks"]
            <= viol["warm_off_shortfall_replica_ticks"]
        )
        # warm capacity costs real money — the report must price it,
        # not hide it
        assert report["hourly_cost"]["warm_on_mean"] > 0
        # both worlds refined through the batched cost seam and filled
        # the PR 9 e2e histogram (the lead-time observable)
        for run in ("warm_on", "warm_off"):
            world = report["runs"][run]
            assert world["cost_dispatches"] >= 1
            assert world["e2e_seconds"]["n"] >= 1
            assert world["e2e_seconds"]["p50_s"] is not None
            assert (
                world["e2e_seconds"]["p99_s"]
                >= world["e2e_seconds"]["p50_s"]
            )

    def test_cost_replay_is_deterministic(self):
        from karpenter_tpu.simulate import simulate_cost

        a = simulate_cost(**self.CONFIG)
        b = simulate_cost(**self.CONFIG)
        assert self._deterministic_view(a) == self._deterministic_view(b)


class TestSimulatePoolGroups:
    """PR 20 satellite (docs/poolgroups.md "Dry-running"): the
    --simulate --poolgroups decode-heavy storm must show the
    coordinated arm HOLDING the declared decode:prefill band (under the
    shared budget, through one joint dispatch per tick) while the
    uncoordinated per-pool baseline violates it — the acceptance
    headline — and the whole report is a pure function of the seed."""

    def test_storm_holds_band_under_coordination_only(self):
        from karpenter_tpu.simulate import simulate_poolgroups

        report = simulate_poolgroups()
        band = report["band"]
        assert band["held_through_storm"] is True
        assert band["coordinated_violation_ticks"] == 0
        assert band["uncoordinated_violation_ticks"] > 0, (
            "the uncoordinated baseline must violate the band — "
            "otherwise the storm proves nothing"
        )
        # the joint point stayed coordinated every tick and spent under
        # the declared shared budget
        on = report["runs"]["coordinated"]
        assert on["coordinated_ticks"] == report["config"]["ticks"]
        assert report["budget"]["under_cap"] is True
        # dispatch collapse: grouped rows leave the per-pool cost
        # ladder (0 cost dispatches) and ride ONE joint dispatch per
        # tick; the baseline keeps the N per-pool cost path
        collapse = report["dispatch_collapse"]
        assert collapse["coordinated_cost_dispatches"] == 0
        assert (
            collapse["coordinated_poolgroup_dispatches"]
            == report["config"]["ticks"]
        )
        assert collapse["uncoordinated_cost_dispatches"] > 0

    def test_replay_digest_is_pinned(self):
        """crc32 of canonical JSON (the constraints-replay discipline):
        the report is deterministic end to end — no wall-time fields —
        so the WHOLE report digests to one pinned value."""
        import json
        import zlib

        from karpenter_tpu.simulate import simulate_poolgroups

        report = simulate_poolgroups()
        canon = json.dumps(
            report, sort_keys=True, separators=(",", ":")
        )
        assert zlib.crc32(canon.encode()) == 762078142
        assert report == simulate_poolgroups()


class TestSimulateConstraints:
    """PR 16 satellite (docs/constraints.md "Dry-running"): the
    --simulate --constraints zonal-outage replay runs the REAL
    producer/encoder/solver path and its report is a pure function of
    the seed — the digests are pinned, not just compared run-to-run."""

    def test_outage_rebalances_without_dropping_the_fence(self):
        from karpenter_tpu.simulate import simulate_constraints

        report = simulate_constraints()
        before, after = report["before"], report["after"]
        # before: the web group spreads evenly and gold fills
        assert before["spread_skew"] == {"web": 0.0}
        assert before["reservation_fill"] == {"gold": 1.0}
        assert before["unschedulable"] == 0
        dead = f"serving-{report['dead_zone']}"
        assert before["groups"][dead]["pending_pods"] > 0
        # after the outage: the dead zone absorbs nothing, the spread
        # rebalances over the survivors (skew stays bounded) and the
        # reservation fence holds
        assert after["groups"][dead]["pending_pods"] == 0
        assert after["groups"][dead]["nodes_needed"] == 0
        assert after["spread_skew"]["web"] <= 1.0
        assert after["reservation_fill"] == {"gold": 1.0}
        assert after["unschedulable"] == 0
        survivors = sum(
            after["groups"][g]["pending_pods"]
            for g in after["groups"]
            if g != dead
        )
        assert survivors == sum(
            before["groups"][g]["pending_pods"]
            for g in before["groups"]
        )
        # the solve stayed healthy the whole replay: constrained
        # encodes compiled, never degraded to the unconstrained wire
        health = report["constraint_health"]
        assert health["compiles"] >= 1
        assert health["fallbacks"] == 0
        assert not health["degraded"]

    def test_replay_digests_are_pinned(self):
        """Deterministic digests over the phase reports (crc32 of
        canonical JSON — stable across processes, unlike hash())."""
        from karpenter_tpu.simulate import simulate_constraints

        report = simulate_constraints()
        assert report["dead_zone"] == "z3"
        assert report["digests"] == {
            "before": 1761739094,
            "after": 2968639679,
        }
        assert report == simulate_constraints()
