"""Multi-chip sharding: the sharded solver must agree exactly with the
single-device solver (bitwise on int outputs), on every mesh shape the
8-device CPU harness can express."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from karpenter_tpu.ops.binpack import binpack
from karpenter_tpu.ops.decision import decide_jit
from karpenter_tpu.parallel import (
    AXIS_GROUPS,
    AXIS_PODS,
    build_mesh,
    dryrun_fleet_step,
    factorize,
    fleet_step,
    pad_binpack_inputs_for_mesh,
    shard_binpack_inputs,
    shard_decision_inputs,
    sharded_binpack,
    sharded_decide,
)
from karpenter_tpu.parallel.mesh import (
    example_binpack_inputs,
    example_decision_inputs,
)


def test_factorize_pods_major():
    assert factorize(8) == (4, 2)
    assert factorize(4) == (2, 2)
    assert factorize(2) == (2, 1)
    assert factorize(1) == (1, 1)
    assert factorize(6) == (3, 2)


def test_build_mesh_shapes():
    mesh = build_mesh(n_devices=8)
    assert mesh.shape[AXIS_PODS] == 4
    assert mesh.shape[AXIS_GROUPS] == 2


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_sharded_binpack_matches_single_device(n_devices):
    inputs = example_binpack_inputs(P_=64, T=8, K=8, L=8, seed=3)
    ref = binpack(inputs, buckets=8)
    mesh = build_mesh(n_devices=n_devices)
    out = sharded_binpack(mesh, inputs, buckets=8)
    np.testing.assert_array_equal(
        np.asarray(out.assigned), np.asarray(ref.assigned)
    )
    np.testing.assert_array_equal(
        np.asarray(out.assigned_count), np.asarray(ref.assigned_count)
    )
    np.testing.assert_array_equal(
        np.asarray(out.nodes_needed), np.asarray(ref.nodes_needed)
    )
    np.testing.assert_array_equal(
        np.asarray(out.lp_bound), np.asarray(ref.lp_bound)
    )
    assert int(out.unschedulable) == int(ref.unschedulable)


@pytest.mark.skipif(
    not __import__("os").environ.get("KARPENTER_SCALE_TESTS"),
    reason="multi-minute compile at scale; battletest sets KARPENTER_SCALE_TESTS=1",
)
def test_sharded_binpack_matches_single_device_at_scale():
    """VERDICT r1 item 4: the sharded-vs-single equality claim held only
    at toy shapes. This pins it at 10k pods x 56 types on the 8-device
    mesh — the same configuration `bench.py --mesh 8 --pods 10000
    --types 56` reports the sharded p50 for."""
    import bench

    inputs = bench.build_inputs(
        pods=10_000, types=56, taints=32, labels=32, seed=0
    )
    ref = jax.device_get(binpack(inputs, buckets=16))
    mesh = build_mesh(n_devices=8)
    out = jax.device_get(sharded_binpack(mesh, inputs, buckets=16))
    np.testing.assert_array_equal(out.assigned, ref.assigned)
    np.testing.assert_array_equal(out.nodes_needed, ref.nodes_needed)
    np.testing.assert_array_equal(out.lp_bound, ref.lp_bound)
    assert int(out.unschedulable) == int(ref.unschedulable)


@pytest.mark.skipif(
    not __import__("os").environ.get("KARPENTER_SCALE_TESTS"),
    reason="timing at scale; battletest sets KARPENTER_SCALE_TESTS=1",
)
def test_sharded_binpack_overhead_bounded():
    """VERDICT r4 weak #4: the mesh rows in docs/BENCHMARKS.md are slow
    enough on host-emulated devices that a sharding-induced 10x
    regression would hide in the tables. Pin the RELATIVE cost instead:
    the 8-device sharded solve must stay within a fixed factor of the
    single-device solve on the SAME backend and inputs (measured ~1.6x
    on the virtual CPU mesh; 8x leaves headroom for noisy runners while
    still failing on any order-of-magnitude sharding regression)."""
    import time

    import bench

    bound = 8.0
    inputs = bench.build_inputs(
        pods=10_000, types=56, taints=32, labels=32, seed=0
    )

    def p50(fn, iters=5):
        fn()  # compile + warm
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return float(np.median(times))

    single = p50(
        lambda: jax.block_until_ready(binpack(inputs, buckets=16))
    )
    mesh = build_mesh(n_devices=8)
    sharded = p50(
        lambda: jax.block_until_ready(
            sharded_binpack(mesh, inputs, buckets=16)
        )
    )
    assert sharded <= bound * single, (
        f"sharded solve {sharded * 1e3:.1f} ms vs single-device "
        f"{single * 1e3:.1f} ms exceeds the {bound}x overhead bound — "
        "a sharding regression, not emulation noise"
    )


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_decide_matches_single_device(n_devices):
    inputs = example_decision_inputs(N=32, M=4, seed=7)
    ref = decide_jit(inputs)
    mesh = build_mesh(n_devices=n_devices)
    out = sharded_decide(mesh, inputs)
    np.testing.assert_array_equal(
        np.asarray(out.desired), np.asarray(ref.desired)
    )
    np.testing.assert_array_equal(
        np.asarray(out.able_to_scale), np.asarray(ref.able_to_scale)
    )
    np.testing.assert_array_equal(
        np.asarray(out.scaling_unbounded), np.asarray(ref.scaling_unbounded)
    )


def test_padding_masks_not_truncates():
    # P=33, T=5 on a 4x2 mesh: P pads to 36, T to 6; results for the real
    # rows/columns must be unchanged
    inputs = example_binpack_inputs(P_=33, T=5, K=8, L=8, seed=11)
    ref = binpack(inputs, buckets=8)
    mesh = build_mesh(n_devices=8)
    padded = pad_binpack_inputs_for_mesh(inputs, mesh)
    assert padded.pod_requests.shape[0] % 4 == 0
    assert padded.group_allocatable.shape[0] % 2 == 0
    out = sharded_binpack(mesh, inputs, buckets=8)
    np.testing.assert_array_equal(
        np.asarray(out.assigned)[:33], np.asarray(ref.assigned)
    )
    np.testing.assert_array_equal(
        np.asarray(out.nodes_needed)[:5], np.asarray(ref.nodes_needed)
    )
    # padding columns got no pods
    assert np.all(np.asarray(out.assigned_count)[5:] == 0)
    assert int(out.unschedulable) == int(ref.unschedulable)


def test_fleet_step_combined():
    mesh = build_mesh(n_devices=8)
    d_in = shard_decision_inputs(mesh, example_decision_inputs(N=16))
    b_in = shard_binpack_inputs(mesh, example_binpack_inputs(P_=32, T=8))
    d_out, b_out = fleet_step(d_in, b_in, buckets=8)
    jax.block_until_ready((d_out, b_out))
    ref_d = decide_jit(example_decision_inputs(N=16))
    np.testing.assert_array_equal(
        np.asarray(d_out.desired)[:16], np.asarray(ref_d.desired)
    )
    total = int(jnp.sum(b_out.assigned_count)) + int(b_out.unschedulable)
    assert total == 32


@pytest.mark.parametrize("n_devices", [1, 4, 8])
def test_dryrun_fleet_step(n_devices):
    dryrun_fleet_step(n_devices)


def test_graft_entry_dryrun_multichip_smoke():
    """The driver-facing sharded-dispatch seam (__graft_entry__
    .dryrun_multichip -> force_virtual_cpu -> dryrun_fleet_step) runs on
    the 8-device CPU mesh — the exact composition the CI driver invokes,
    so the hook can't rot independently of the mesh tests above."""
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)


def test_sharded_outputs_sliced_to_input_sizes():
    """Mesh padding must not leak: output shapes equal input P/T even when
    padding occurred (P=33->36, T=5->6 on a 4x2 mesh)."""
    inputs = example_binpack_inputs(P_=33, T=5, K=8, L=8, seed=13)
    mesh = build_mesh(n_devices=8)
    out = sharded_binpack(mesh, inputs, buckets=8)
    assert out.assigned.shape == (33,)
    assert out.nodes_needed.shape == (5,)
    ref = binpack(inputs, buckets=8)
    assert int(np.sum(np.asarray(out.assigned) == -1)) == int(
        np.sum(np.asarray(ref.assigned) == -1)
    )
    d_in = example_decision_inputs(N=13, M=3, seed=17)
    d_out = sharded_decide(mesh, d_in)
    assert d_out.desired.shape == (13,)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_weighted_binpack_matches_single_device(n_devices):
    """pod_weight (deduplicated shape rows) must ride the pods mesh axis
    like every other row-major array: sharded == single-device on a
    weighted problem, and padding rows (weight 0) stay inert."""
    import jax.numpy as jnp

    import dataclasses

    rng = np.random.default_rng(21)
    weighted = dataclasses.replace(
        example_binpack_inputs(P_=37, T=5, K=8, L=8, seed=21),
        pod_weight=jnp.asarray(rng.integers(1, 50, 37).astype(np.int32)),
    )
    ref = jax.device_get(binpack(weighted, buckets=8))
    mesh = build_mesh(n_devices=n_devices)
    out = jax.device_get(sharded_binpack(mesh, weighted, buckets=8))
    np.testing.assert_array_equal(out.assigned, ref.assigned)
    np.testing.assert_array_equal(out.assigned_count, ref.assigned_count)
    np.testing.assert_array_equal(out.nodes_needed, ref.nodes_needed)
    np.testing.assert_array_equal(out.lp_bound, ref.lp_bound)
    assert int(out.unschedulable) == int(ref.unschedulable)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_score_binpack_matches_single_device(n_devices):
    """pod_group_score shards over both mesh axes like the forbidden
    mask; the cross-shard argmax (max-score + min-index) must equal the
    single-device assignment exactly."""
    import dataclasses

    import jax.numpy as jnp

    rng = np.random.default_rng(29)
    inputs = dataclasses.replace(
        example_binpack_inputs(P_=37, T=5, K=8, L=8, seed=29),
        pod_weight=jnp.asarray(rng.integers(1, 50, 37).astype(np.int32)),
        pod_group_score=jnp.asarray(
            rng.integers(0, 100, (37, 5)).astype(np.float32)
        ),
    )
    ref = jax.device_get(binpack(inputs, buckets=8))
    mesh = build_mesh(n_devices=n_devices)
    out = jax.device_get(sharded_binpack(mesh, inputs, buckets=8))
    np.testing.assert_array_equal(out.assigned, ref.assigned)
    np.testing.assert_array_equal(out.assigned_count, ref.assigned_count)
    np.testing.assert_array_equal(out.nodes_needed, ref.nodes_needed)
    assert int(out.unschedulable) == int(ref.unschedulable)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_forbidden_binpack_matches_single_device(n_devices):
    """pod_group_forbidden (required node affinity) is the one 2D
    pods x groups input: it shards over BOTH mesh axes and must leave
    sharded == single-device, with padding on both dims inert."""
    import dataclasses

    import jax.numpy as jnp

    rng = np.random.default_rng(27)
    inputs = dataclasses.replace(
        example_binpack_inputs(P_=37, T=5, K=8, L=8, seed=27),
        pod_weight=jnp.asarray(rng.integers(1, 50, 37).astype(np.int32)),
        pod_group_forbidden=jnp.asarray(rng.random((37, 5)) < 0.4),
    )
    ref = jax.device_get(binpack(inputs, buckets=8))
    mesh = build_mesh(n_devices=n_devices)
    out = jax.device_get(sharded_binpack(mesh, inputs, buckets=8))
    np.testing.assert_array_equal(out.assigned, ref.assigned)
    np.testing.assert_array_equal(out.assigned_count, ref.assigned_count)
    np.testing.assert_array_equal(out.nodes_needed, ref.nodes_needed)
    np.testing.assert_array_equal(out.lp_bound, ref.lp_bound)
    assert int(out.unschedulable) == int(ref.unschedulable)


def test_sliced_mesh_matches_single_device():
    """3D slice×pods×groups mesh (multi-host DCN model): pod rows shard
    over (slice, pods); outputs must equal the single-device solve, and
    the decision kernel must shard its fleet axis the same way."""
    import dataclasses

    import jax.numpy as jnp
    from karpenter_tpu.parallel.mesh import (
        example_decision_inputs,
        sharded_decide,
    )
    from karpenter_tpu.ops.decision import decide_jit

    mesh = build_mesh(n_devices=8, slices=2)
    assert dict(mesh.shape) == {"slice": 2, "pods": 2, "groups": 2}

    rng = np.random.default_rng(33)
    weighted = dataclasses.replace(
        example_binpack_inputs(P_=45, T=6, K=8, L=8, seed=33),
        pod_weight=jnp.asarray(rng.integers(1, 20, 45).astype(np.int32)),
        # forbidden is the one (slice, pods) x groups sharded operand:
        # cover its two-axis row spec on the 3D mesh too
        pod_group_forbidden=jnp.asarray(rng.random((45, 6)) < 0.3),
    )
    ref = jax.device_get(binpack(weighted, buckets=8))
    out = jax.device_get(sharded_binpack(mesh, weighted, buckets=8))
    np.testing.assert_array_equal(out.assigned, ref.assigned)
    np.testing.assert_array_equal(out.nodes_needed, ref.nodes_needed)
    np.testing.assert_array_equal(out.lp_bound, ref.lp_bound)
    assert int(out.unschedulable) == int(ref.unschedulable)

    d_in = example_decision_inputs(N=19, M=3, seed=5)
    d_ref = jax.device_get(decide_jit(d_in))
    d_out = jax.device_get(sharded_decide(mesh, d_in))
    np.testing.assert_array_equal(d_out.desired, d_ref.desired)
    np.testing.assert_array_equal(
        d_out.able_to_scale, d_ref.able_to_scale
    )


# ---------------------------------------------------------------------------
# PR 8 satellites: padding/sharding helper property pins + the honest
# compat surface behind the sharded dispatch strategy.
# ---------------------------------------------------------------------------


def _full_operand_inputs(P_: int, T: int, seed: int):
    """example inputs carrying EVERY optional operand the encoder can
    emit — the widest pytree the mesh helpers must round-trip."""
    import dataclasses

    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    return dataclasses.replace(
        example_binpack_inputs(P_=P_, T=T, K=8, L=8, seed=seed),
        pod_weight=jnp.asarray(
            rng.integers(1, 9, P_).astype(np.int32)
        ),
        pod_group_forbidden=jnp.asarray(rng.random((P_, T)) < 0.25),
        pod_group_score=jnp.asarray(
            rng.integers(0, 100, (P_, T)).astype(np.float32)
        ),
        pod_exclusive=jnp.asarray(rng.random(P_) < 0.2),
        pod_priority=jnp.asarray(
            rng.integers(0, 4, P_).astype(np.int32)
        ),
        group_tier=jnp.asarray(
            (rng.random(T) < 0.4).astype(np.int32)
        ),
    )


def test_pad_shard_unpad_is_identity_property():
    """The satellite property pin: for arbitrary NON-divisible shapes,
    pad_binpack_inputs_for_mesh -> device_put with shardings -> slice
    back to the original axes is the IDENTITY on every operand (padding
    masks, never mutates), and the padded axes are mesh-divisible."""
    from karpenter_tpu.parallel import (
        mesh_extents,
        shard_binpack_inputs,
    )

    mesh = build_mesh(n_devices=8)
    rows, cols = mesh_extents(mesh)
    rng = np.random.default_rng(99)
    for trial in range(6):
        P_ = int(rng.integers(1, 120))
        T = int(rng.integers(1, 15))
        inputs = _full_operand_inputs(P_, T, seed=100 + trial)
        padded = pad_binpack_inputs_for_mesh(inputs, mesh)
        assert padded.pod_requests.shape[0] % rows == 0
        assert padded.group_allocatable.shape[0] % cols == 0
        sharded = shard_binpack_inputs(mesh, inputs)
        for name, axis_pod in (
            ("pod_requests", True), ("pod_valid", True),
            ("pod_intolerant", True), ("pod_required", True),
            ("group_allocatable", False), ("group_taints", False),
            ("group_labels", False), ("pod_weight", True),
            ("pod_group_forbidden", True), ("pod_group_score", True),
            ("pod_exclusive", True), ("pod_priority", True),
            ("group_tier", False),
        ):
            orig = np.asarray(getattr(inputs, name))
            got = np.asarray(getattr(sharded, name))
            n = P_ if axis_pod else T
            if name in ("pod_group_forbidden", "pod_group_score"):
                got = got[:P_, :T]
            else:
                got = got[:n]
            np.testing.assert_array_equal(
                got, orig, err_msg=f"trial {trial}: {name}"
            )


def test_pad_for_mesh_carries_priority_operands():
    """Regression: pad_binpack_inputs_for_mesh used to rebuild the
    pytree WITHOUT pod_priority/group_tier, silently stripping the
    PR 6 steering operands from any padded sharded solve."""
    inputs = _full_operand_inputs(33, 5, seed=7)
    mesh = build_mesh(n_devices=8)
    padded = pad_binpack_inputs_for_mesh(inputs, mesh)
    assert padded.pod_priority is not None
    assert padded.group_tier is not None
    # the padding itself is inert: priority 0 (no steering), tier 0
    # (on-demand) on rows/columns that are invalid/infeasible anyway
    assert np.all(np.asarray(padded.pod_priority)[33:] == 0)
    assert np.all(np.asarray(padded.group_tier)[5:] == 0)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_matches_unsharded_matches_numpy(n_devices):
    """The three-way parity pin behind `make bench-shard`: the sharded
    program == the single-device program == the numpy mirror on a
    non-divisible full-operand problem (integer outputs exact; lp_bound
    rides the established numpy contract of ±1 at f32 reduction-order
    boundaries)."""
    from karpenter_tpu.ops.numpy_binpack import binpack_numpy

    inputs = _full_operand_inputs(77, 9, seed=5)
    ref = jax.device_get(binpack(inputs, buckets=8))
    ref_np = binpack_numpy(inputs, buckets=8)
    mesh = build_mesh(n_devices=n_devices)
    out = jax.device_get(sharded_binpack(mesh, inputs, buckets=8))
    for mirror, label in ((ref, "xla"), (ref_np, "numpy")):
        np.testing.assert_array_equal(
            out.assigned, np.asarray(mirror.assigned), err_msg=label
        )
        np.testing.assert_array_equal(
            out.assigned_count, np.asarray(mirror.assigned_count),
            err_msg=label,
        )
        np.testing.assert_array_equal(
            out.nodes_needed, np.asarray(mirror.nodes_needed),
            err_msg=label,
        )
        assert int(out.unschedulable) == int(mirror.unschedulable)
        assert (
            np.abs(
                np.asarray(out.lp_bound, np.int64)
                - np.asarray(mirror.lp_bound, np.int64)
            ).max(initial=0)
            <= 1
        ), label


def test_build_mesh_shape_override():
    """The --shard-mesh knob: explicit (pods, groups) extents replace
    the pods-major factorization; impossible shapes fail loudly."""
    mesh = build_mesh(shape=(8, 1))
    assert mesh.shape[AXIS_PODS] == 8
    assert mesh.shape[AXIS_GROUPS] == 1
    mesh = build_mesh(shape=(2, 4))
    assert mesh.shape[AXIS_PODS] == 2
    assert mesh.shape[AXIS_GROUPS] == 4
    with pytest.raises(ValueError):
        build_mesh(shape=(16, 2))  # more devices than exist
    with pytest.raises(ValueError):
        build_mesh(shape=(4, 2), slices=2)  # mutually exclusive


def test_compat_surface_is_honest():
    """parallel/compat.py must expose the modern sharding names and must
    NOT carry the long-dead `jax.interpreters.sharded_jit` rung: the
    pinned JAX (pyproject: >=0.4.30) deleted that module years ago, so
    a ladder reaching for it would be unreachable dead weight
    misrepresenting what this repo supports."""
    import inspect

    from karpenter_tpu.parallel import compat

    assert compat.PartitionSpec is jax.sharding.PartitionSpec
    assert compat.Mesh is jax.sharding.Mesh
    assert compat.NamedSharding is jax.sharding.NamedSharding
    assert callable(compat.shard_map)
    assert callable(compat.pjit)
    # no executable line reaches for the dead module (the docstring
    # documenting WHY the rung is pruned is allowed to name it)
    tree = __import__("ast").parse(inspect.getsource(compat))
    for node in __import__("ast").walk(tree):
        module = getattr(node, "module", "") or ""
        assert "sharded_jit" not in module, "dead compat rung is back"
    # and the module the pruned rung reached for really is gone
    import importlib

    with pytest.raises(ImportError):
        importlib.import_module("jax.interpreters.sharded_jit")


# ---------------------------------------------------------------------------
# PR 13: the mesh is FINISHED — forecast and preempt ride the sharded
# dispatch path with sharded == single-device == numpy parity pins
# (closing the PR 8 "no sharded parity pin yet" caveat), and the decide
# kernel's fleet axis shards behind the same threshold.
# ---------------------------------------------------------------------------


def _forecast_problem(S=37, T=24, seed=11):
    """Seeded adversarial forecast histories (mixed models, gaps,
    out-of-range seasons) — NOT mesh-divisible on purpose."""
    from karpenter_tpu.forecast import models as M

    rng = np.random.RandomState(seed)
    ticks = np.arange(T, dtype=np.float32)[None, :]
    values = (
        rng.uniform(0, 300, (S, 1))
        + rng.uniform(-2, 4, (S, 1)) * ticks * 10
        + rng.normal(0, 4, (S, T))
    ).astype(np.float32)
    times = ((ticks - (T - 1)) * 10.0).astype(np.float32)
    horizon = rng.uniform(10, 200, S).astype(np.float32)
    return M.ForecastInputs(
        values=values,
        valid=rng.rand(S, T) > 0.3,
        times=np.broadcast_to(times, (S, T)).copy(),
        weights=np.ones((S, T), np.float32),
        horizon=horizon,
        step_s=rng.uniform(0, 30, S).astype(np.float32),
        model=rng.choice(
            [M.MODEL_LINEAR, M.MODEL_HOLT_WINTERS], S
        ).astype(np.int32),
        season=rng.choice([0, 1, 4, 8, 3 * T], S).astype(np.int32),
        alpha=rng.uniform(0.1, 1.0, S).astype(np.float32),
        beta=rng.uniform(0.05, 1.0, S).astype(np.float32),
        gamma=rng.uniform(0.05, 1.0, S).astype(np.float32),
    )


def service_t_bucket(inputs) -> int:
    """The history bucket the SolverService pads forecast inputs to."""
    from karpenter_tpu.solver.bucketing import bucket_up
    from karpenter_tpu.solver.service import FORECAST_T_FLOOR

    return bucket_up(
        int(np.asarray(inputs.values).shape[1]), FORECAST_T_FLOOR
    )


def _preempt_problem(c=21, n=6, v=50, r=3, seed=13):
    """Seeded eviction problem honoring the victim sort contract; the
    candidate axis is NOT mesh-divisible on purpose."""
    from karpenter_tpu.ops.preempt import PreemptInputs

    rng = np.random.default_rng(seed)
    victim_node = np.sort(rng.integers(0, n, v)).astype(np.int32)
    victim_priority = np.zeros(v, np.int32)
    for col in range(n):
        seg = victim_node == col
        victim_priority[seg] = np.sort(
            rng.integers(0, 300, int(seg.sum()))
        )
    return PreemptInputs(
        pod_requests=rng.uniform(0.1, 5.0, (c, r)).astype(np.float32),
        pod_priority=rng.integers(0, 400, c).astype(np.int32),
        pod_valid=rng.random(c) < 0.9,
        pod_node_forbidden=rng.random((c, n)) < 0.15,
        node_free=rng.uniform(0.0, 3.0, (n, r)).astype(np.float32),
        node_tier=(rng.random(n) < 0.3).astype(np.int32),
        victim_requests=rng.uniform(0.05, 2.0, (v, r)).astype(np.float32),
        victim_priority=victim_priority,
        victim_node=victim_node,
        victim_valid=rng.random(v) < 0.95,
        victim_evictable=rng.random(v) < 0.9,
    )


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_forecast_matches_single_device_and_numpy(n_devices):
    """Forecast parity pin: the series axis shards over the mesh rows
    and every recurrence is per-series, so sharded == single-device ==
    forecast_numpy BITWISE (the forecast FMA-parity contract composes
    through GSPMD untouched)."""
    from karpenter_tpu.forecast import models as M
    from karpenter_tpu.parallel import sharded_forecast

    inputs = _forecast_problem()
    ref = jax.device_get(jax.jit(M.forecast)(inputs))
    ref_np = M.forecast_numpy(inputs)
    mesh = build_mesh(n_devices=n_devices)
    out = jax.device_get(sharded_forecast(mesh, inputs))
    for mirror, label in ((ref, "xla"), (ref_np, "numpy")):
        for field in ("point", "sigma2", "n_valid"):
            np.testing.assert_array_equal(
                np.asarray(getattr(out, field)),
                np.asarray(getattr(mirror, field)),
                err_msg=f"{label}.{field}",
            )


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_preempt_matches_single_device_and_numpy(n_devices):
    """Preempt parity pin: the candidate axis shards over the mesh rows
    (candidates are planned data-parallel), nodes/victims replicate, and
    all capacity arithmetic is integer — sharded == single-device ==
    preempt_numpy BITWISE, including the cross-shard unplaceable sum."""
    from karpenter_tpu.ops.preempt import preempt_numpy, preempt_plan
    from karpenter_tpu.parallel import sharded_preempt

    inputs = _preempt_problem()
    ref = jax.device_get(preempt_plan(inputs))
    ref_np = preempt_numpy(inputs)
    mesh = build_mesh(n_devices=n_devices)
    out = jax.device_get(sharded_preempt(mesh, inputs))
    for mirror, label in ((ref, "xla"), (ref_np, "numpy")):
        for field in (
            "chosen_node", "evict_count", "evict_mask", "unplaceable"
        ):
            np.testing.assert_array_equal(
                np.asarray(getattr(out, field)),
                np.asarray(getattr(mirror, field)),
                err_msg=f"{label}.{field}",
            )


def test_service_routes_forecast_preempt_decide_through_mesh():
    """The PRODUCTION route: a SolverService with the threshold forced
    low must route forecast, preempt, AND decide through its sharded
    dispatch strategy — bit-identical to the single-device mirrors —
    certifying the seam every caller actually takes."""
    from karpenter_tpu.forecast import models as M
    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.ops.decision import decide_jit
    from karpenter_tpu.ops.preempt import preempt_numpy
    from karpenter_tpu.solver import SolverService

    service = SolverService(
        registry=GaugeRegistry(), shard_threshold=1, backend="xla"
    )
    try:
        f_in = _forecast_problem(S=29, T=20, seed=3)
        f_out = service.forecast(f_in, backend="xla")
        # reference = the service's own numpy rung: both pad T up the
        # same bucket ladder, which matters for season > T series (the
        # kernel clamps season to the PADDED T — a documented
        # T-sensitivity, identical on every rung of one service)
        f_ref = M.forecast_numpy(
            M.pad_forecast_inputs(f_in, service_t_bucket(f_in))
        )
        for field in ("point", "sigma2", "n_valid"):
            np.testing.assert_array_equal(
                np.asarray(getattr(f_out, field)),
                np.asarray(getattr(f_ref, field)),
                err_msg=field,
            )
        p_in = _preempt_problem(seed=5)
        p_out = service.preempt(p_in, backend="xla")
        p_ref = preempt_numpy(p_in)
        for field in (
            "chosen_node", "evict_count", "evict_mask", "unplaceable"
        ):
            np.testing.assert_array_equal(
                np.asarray(getattr(p_out, field)),
                np.asarray(getattr(p_ref, field)),
                err_msg=field,
            )
        d_in = example_decision_inputs(N=33, M=3, seed=9)
        d_out = service.decide(d_in)
        d_ref = decide_jit(d_in)
        np.testing.assert_array_equal(
            np.asarray(d_out.desired), np.asarray(d_ref.desired)
        )
        np.testing.assert_array_equal(
            np.asarray(d_out.able_to_scale),
            np.asarray(d_ref.able_to_scale),
        )
        # all three families actually rode the mesh
        assert service.stats.shard_dispatches >= 3, service.stats
    finally:
        service.close()


def test_sharded_forecast_failure_walks_the_ladder():
    """A shard-routed forecast whose device path faults retries
    single-device, then lands on the numpy mirror — the same
    shard -> single-device -> numpy ladder bin-packs ride — and the
    caller still gets the bit-identical answer."""
    from karpenter_tpu.faults import injected_faults
    from karpenter_tpu.forecast import models as M
    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.solver import SolverService

    service = SolverService(
        registry=GaugeRegistry(), shard_threshold=1, backend="xla"
    )
    try:
        inputs = _forecast_problem(S=17, T=20, seed=21)
        with injected_faults(seed=3) as reg:
            reg.plan("forecast.predict", mode="error")
            out = service.forecast(inputs, backend="xla")
        ref = M.forecast_numpy(
            M.pad_forecast_inputs(inputs, service_t_bucket(inputs))
        )
        for field in ("point", "sigma2", "n_valid"):
            np.testing.assert_array_equal(
                np.asarray(getattr(out, field)),
                np.asarray(getattr(ref, field)),
                err_msg=field,
            )
        assert service.stats.shard_fallbacks >= 1
        assert service.stats.fallbacks >= 1  # numpy rung answered
        # one shard failure stops routing new traffic onto the mesh
        # until the recovery-boot seam re-arms it
        assert service._shard_broken
        service.reset_caches()
        assert not service._shard_broken
    finally:
        service.close()


# ---------------------------------------------------------------------------
# PR 16: constraint operands through the mesh — sharded == single ==
# numpy BITWISE on compiler-generated constrained inputs, and the pad
# helper carries all six new operands (the PR 8 silent-drop bug class).
# ---------------------------------------------------------------------------


def _constrained_inputs(seed: int):
    """Compiler-generated constrained BinPackInputs (the exactness
    contract only holds for compiler output: spread rows pre-split at
    cap boundaries)."""
    from karpenter_tpu.api.core import (
        Container,
        ObjectMeta,
        Pod,
        PodSpec,
        RESERVATION_LABEL,
        ZONE_LABEL,
        resource_list,
    )
    from karpenter_tpu.constraints import ConstraintGroup, SpreadSpec
    from karpenter_tpu.metrics.producers.pendingcapacity import (
        encode_snapshot,
    )
    from karpenter_tpu.store.columnar import snapshot_from_pods

    rng = np.random.default_rng(seed)
    pods = []
    for p in range(int(rng.integers(16, 40))):
        team = int(rng.integers(0, 6))
        labels = {"team": f"t{team}"} if team < 4 else {}
        pods.append(
            Pod(
                metadata=ObjectMeta(name=f"p{p}", labels=labels),
                spec=PodSpec(
                    node_name="",
                    containers=[
                        Container(
                            requests=resource_list(
                                cpu=str(int(rng.integers(1, 3))),
                                memory="1Gi",
                            )
                        )
                    ],
                ),
            )
        )
    alloc = {"cpu": 8.0, "memory": 32.0, "pods": 32.0}
    profiles = [
        (dict(alloc), {(ZONE_LABEL, "z1")}, set()),
        (dict(alloc), {(ZONE_LABEL, "z2")}, set()),
        (dict(alloc), {(ZONE_LABEL, "z3")}, set()),
        (dict(alloc), {(RESERVATION_LABEL, "gold")}, set()),
        (dict(alloc), set(), set()),
    ]
    groups = [
        ConstraintGroup(
            name="web", pod_selector={"team": "t0"}, spread=SpreadSpec()
        ),
        ConstraintGroup(
            name="gold", pod_selector={"team": "t1"}, reservation="gold"
        ),
        ConstraintGroup(
            name="solo", pod_selector={"team": "t2"}, anti_affinity=True
        ),
        ConstraintGroup(
            name="tight", pod_selector={"team": "t3"}, compact=True
        ),
    ]
    return encode_snapshot(
        snapshot_from_pods(pods), profiles, constraints=groups
    )


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_constrained_matches_unsharded_matches_numpy(n_devices):
    """The PR 16 acceptance pin: with constraint operands present, the
    sharded program == the single-device program == the numpy mirror,
    bitwise on integer outputs."""
    from karpenter_tpu.ops import binpack as B
    from karpenter_tpu.ops.numpy_binpack import binpack_numpy

    inputs = _constrained_inputs(seed=42)
    assert B.has_constraint_operands(inputs)
    ref = jax.device_get(binpack(inputs, buckets=8))
    ref_np = binpack_numpy(inputs, buckets=8)
    mesh = build_mesh(n_devices=n_devices)
    out = jax.device_get(sharded_binpack(mesh, inputs, buckets=8))
    for mirror, label in ((ref, "xla"), (ref_np, "numpy")):
        np.testing.assert_array_equal(
            out.assigned, np.asarray(mirror.assigned), err_msg=label
        )
        np.testing.assert_array_equal(
            out.assigned_count, np.asarray(mirror.assigned_count),
            err_msg=label,
        )
        np.testing.assert_array_equal(
            out.nodes_needed, np.asarray(mirror.nodes_needed),
            err_msg=label,
        )
        assert int(out.unschedulable) == int(mirror.unschedulable)


def test_pad_for_mesh_carries_constraint_operands():
    """Regression (the PR 8 silent-drop bug class): the pad helper must
    rebuild the pytree WITH all six constraint operands, and padding
    must be inert — claim 0 / slot 0 / class-0 rows, reservation 0 /
    domain 0 columns, spread_cap untouched."""
    inputs = _constrained_inputs(seed=43)
    P_ = int(np.asarray(inputs.pod_valid).shape[0])
    T = int(np.asarray(inputs.group_allocatable).shape[0])
    mesh = build_mesh(n_devices=8)
    padded = pad_binpack_inputs_for_mesh(inputs, mesh)
    for name in (
        "pod_claim", "group_reservation", "pod_pack_class",
        "pod_spread_slot", "group_domain", "spread_cap",
    ):
        if getattr(inputs, name) is None:
            continue
        assert getattr(padded, name) is not None, name
    if padded.pod_claim is not None:
        assert np.all(np.asarray(padded.pod_claim)[P_:] == 0)
    if padded.group_reservation is not None:
        assert np.all(np.asarray(padded.group_reservation)[T:] == 0)
    if padded.pod_spread_slot is not None:
        assert np.all(np.asarray(padded.pod_spread_slot)[P_:] == 0)
    if padded.group_domain is not None:
        assert np.all(np.asarray(padded.group_domain)[T:] == 0)
    if padded.pod_pack_class is not None:
        assert not np.asarray(padded.pod_pack_class)[P_:].any()
    if padded.spread_cap is not None:
        np.testing.assert_array_equal(
            np.asarray(padded.spread_cap),
            np.asarray(inputs.spread_cap),
        )
