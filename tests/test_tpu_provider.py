"""TPU pod-slice pool provider: the TPU-native node-group analog of the
reference's AWS providers (managednodegroup.go observation posture, plus a
real Stabilized instead of the reference's TODO-true)."""

import pytest

from karpenter_tpu.api.core import (
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    resource_list,
)
from karpenter_tpu.api.scalablenodegroup import (
    TPU_POD_SLICE_POOL,
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.cloudprovider import Options
from karpenter_tpu.cloudprovider.tpu import (
    NODE_POOL_LABEL,
    TPU_TOPOLOGY_LABEL,
    TPUFactory,
    TPUPodSlicePool,
    parse_pool_id,
)
from karpenter_tpu.runtime import KarpenterRuntime
from karpenter_tpu.store import Store

POOL_ID = "projects/p/locations/us-central2-b/clusters/c/nodePools/train"
POOL_ID_SHORT = "projects/p/locations/us-central2-b/nodePools/train"


class FakeContainerAPI:
    def __init__(self):
        self.sizes = {}
        self.operations = []
        self.want_err = None

    def set_node_pool_size(self, project, location, cluster, pool, size):
        if self.want_err:
            raise self.want_err
        self.sizes[(project, location, cluster, pool)] = size

    def pending_operations(self, project, location, cluster, pool):
        return list(self.operations)


def pool_node(name, pool="train", ready=True, topology=None):
    labels = {NODE_POOL_LABEL: pool}
    if topology:
        labels[TPU_TOPOLOGY_LABEL] = topology
    return Node(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=NodeSpec(),
        status=NodeStatus(
            allocatable=resource_list(cpu="4", memory="8Gi", pods="16"),
            conditions=[NodeCondition("Ready", "True" if ready else "False")],
        ),
    )


class TestParsePoolID:
    def test_full_form(self):
        assert parse_pool_id(POOL_ID) == ("p", "us-central2-b", "c", "train")

    def test_short_form(self):
        assert parse_pool_id(POOL_ID_SHORT) == (
            "p",
            "us-central2-b",
            "",
            "train",
        )

    @pytest.mark.parametrize(
        "bad", ["train", "projects/p/nodePools/x", "projects//locations/l/nodePools/x"]
    )
    def test_invalid(self, bad):
        with pytest.raises(ValueError):
            parse_pool_id(bad)


class TestReplicas:
    def test_counts_ready_single_host_slices(self):
        store = Store()
        store.create(pool_node("n1"))
        store.create(pool_node("n2"))
        store.create(pool_node("n3", ready=False))
        store.create(pool_node("other", pool="serve"))
        pool = TPUPodSlicePool(POOL_ID, FakeContainerAPI(), store)
        assert pool.get_replicas() == 2

    def test_multi_host_slices_count_whole_slices(self):
        store = Store()
        # 2x4 topology = 8 chips = 2 hosts per slice; 3 ready hosts = 1 slice
        for i in range(3):
            store.create(pool_node(f"n{i}", topology="2x4"))
        pool = TPUPodSlicePool(POOL_ID, FakeContainerAPI(), store)
        assert pool.get_replicas() == 1

    def test_set_replicas_actuates_api(self):
        api = FakeContainerAPI()
        TPUPodSlicePool(POOL_ID, api, Store()).set_replicas(4)
        assert api.sizes[("p", "us-central2-b", "c", "train")] == 4

    def test_resize_error_is_retryable(self):
        from karpenter_tpu.controllers.errors import is_retryable

        api = FakeContainerAPI()
        api.want_err = RuntimeError("stockout")
        with pytest.raises(Exception) as e:
            TPUPodSlicePool(POOL_ID, api, Store()).set_replicas(4)
        assert is_retryable(e.value)


class TestStabilized:
    def test_stable_when_no_operations(self):
        pool = TPUPodSlicePool(POOL_ID, FakeContainerAPI(), Store())
        assert pool.stabilized() == (True, "")

    def test_unstable_during_resize(self):
        api = FakeContainerAPI()
        api.operations = ["resize-op-1"]
        stable, message = TPUPodSlicePool(POOL_ID, api, Store()).stabilized()
        assert not stable
        assert "resize-op-1" in message

    def test_pending_operations_error_is_retryable(self):
        """A GKE API blip polling operations must not deactivate the SNG —
        same transient posture as set_replicas resize errors."""
        from karpenter_tpu.controllers.errors import is_retryable

        class ThrowingAPI(FakeContainerAPI):
            def pending_operations(self, project, location, cluster, pool):
                raise RuntimeError("throttled")

        with pytest.raises(Exception) as e:
            TPUPodSlicePool(POOL_ID, ThrowingAPI(), Store()).stabilized()
        assert is_retryable(e.value)


class TestThroughController:
    def test_scale_up_via_controller(self):
        store = Store()
        api = FakeContainerAPI()
        provider = TPUFactory(Options(store=store), container_api=api)
        runtime = KarpenterRuntime(store=store, cloud_provider_factory=provider)
        store.create(pool_node("n1"))
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="train"),
                spec=ScalableNodeGroupSpec(
                    type=TPU_POD_SLICE_POOL, id=POOL_ID, replicas=3
                ),
            )
        )
        runtime.manager.reconcile_all()
        sng = store.get("ScalableNodeGroup", "default", "train")
        assert sng.status.replicas == 1  # observed from store
        assert api.sizes[("p", "us-central2-b", "c", "train")] == 3
        assert sng.status_conditions().is_happy()

    def test_validation_rejects_bad_pool_id(self):
        sng = ScalableNodeGroup(
            metadata=ObjectMeta(name="bad"),
            spec=ScalableNodeGroupSpec(type=TPU_POD_SLICE_POOL, id="nope"),
        )
        with pytest.raises(Exception):
            sng.validate()


class TestChipsPerHostDerivation:
    def test_v5e_single_host_8_chip_slice(self):
        """A 2x4 v5e slice on ONE 8-chip host must count 1 slice per host,
        not 1 per 2 hosts."""
        from karpenter_tpu.utils.quantity import Quantity

        store = Store()
        for i in range(3):
            n = pool_node(f"n{i}", topology="2x4")
            n.status.allocatable["google.com/tpu"] = Quantity.parse("8")
            store.create(n)
        pool = TPUPodSlicePool(POOL_ID, FakeContainerAPI(), store)
        assert pool.get_replicas() == 3

    def test_v4_multi_host_slice(self):
        from karpenter_tpu.utils.quantity import Quantity

        store = Store()
        # 2x2x4 = 16 chips, 4 chips/host -> 4 hosts per slice; 8 ready
        # hosts -> 2 slices
        for i in range(8):
            n = pool_node(f"n{i}", topology="2x2x4")
            n.status.allocatable["google.com/tpu"] = Quantity.parse("4")
            store.create(n)
        pool = TPUPodSlicePool(POOL_ID, FakeContainerAPI(), store)
        assert pool.get_replicas() == 2


class TestNodeTemplate:
    """Scale-from-zero seam: template() surfaces the pool's declared host
    shape when the bound API exposes node_pool_template; absent that, None
    (live nodes are then the only shape source)."""

    def test_no_template_hook_returns_none(self):
        pool = TPUPodSlicePool(POOL_ID, FakeContainerAPI(), Store())
        assert pool.template() is None

    def test_template_from_api(self):
        class TemplateAPI(FakeContainerAPI):
            def node_pool_template(self, project, location, cluster, pool):
                assert (project, location, cluster, pool) == (
                    "p", "us-central2-b", "c", "train",
                )
                return {
                    "allocatable": {
                        "cpu": "240",
                        "memory": "400Gi",
                        "google.com/tpu": "4",
                    },
                    "labels": {TPU_TOPOLOGY_LABEL: "2x2x4"},
                }

        pool = TPUPodSlicePool(POOL_ID, TemplateAPI(), Store())
        template = pool.template()
        assert template.allocatable["google.com/tpu"].to_float() == 4
        assert template.allocatable["cpu"].to_float() == 240
        # pool label is stamped so selectors over the pool match
        assert template.labels[NODE_POOL_LABEL] == "train"
        assert template.labels[TPU_TOPOLOGY_LABEL] == "2x2x4"

    def test_template_taints_convert_to_core_taints(self):
        """GKE returns taints as dicts with NO_SCHEDULE-style enum
        effects; template() must yield api.core.Taint with core/v1
        effects, or the resolver's attribute access / effect filter
        breaks on exactly the tainted pools TPU pools are."""
        class TaintedAPI(FakeContainerAPI):
            def node_pool_template(self, project, location, cluster, pool):
                return {
                    "allocatable": {"cpu": "240", "google.com/tpu": "4"},
                    "taints": [
                        {
                            "key": "google.com/tpu",
                            "value": "present",
                            "effect": "NO_SCHEDULE",
                        },
                        {
                            "key": "already-core",
                            "effect": "NoExecute",
                        },
                    ],
                }

        pool = TPUPodSlicePool(POOL_ID, TaintedAPI(), Store())
        taints = pool.template().taints
        assert [(t.key, t.value, t.effect) for t in taints] == [
            ("google.com/tpu", "present", "NoSchedule"),
            ("already-core", "", "NoExecute"),
        ]

    def test_template_api_returning_none(self):
        class NoneAPI(FakeContainerAPI):
            def node_pool_template(self, project, location, cluster, pool):
                return None

        pool = TPUPodSlicePool(POOL_ID, NoneAPI(), Store())
        assert pool.template() is None


class TestPubSubQueue:
    """The GCP analog of the reference's SQS queue (sqsqueue.go) — both
    gauges real (the reference stubs message age, sqsqueue.go:78-80)."""

    SUB_ID = "projects/p/subscriptions/work"

    class MetricsAPI:
        def __init__(self, undelivered=0, age=0, err=None):
            self.undelivered, self.age, self.err = undelivered, age, err

        def num_undelivered_messages(self, project, subscription):
            if self.err:
                raise self.err
            assert (project, subscription) == ("p", "work")
            return self.undelivered

        def oldest_unacked_message_age_seconds(self, project, subscription):
            if self.err:
                raise self.err
            return self.age

    def test_reads_depth_and_age(self):
        from karpenter_tpu.cloudprovider.tpu import PubSubSubscriptionQueue

        queue = PubSubSubscriptionQueue(
            self.SUB_ID, self.MetricsAPI(undelivered=41, age=17)
        )
        assert queue.name() == "work"
        assert queue.length() == 41
        assert queue.oldest_message_age_seconds() == 17

    def test_monitoring_blip_is_retryable(self):
        from karpenter_tpu.cloudprovider.tpu import PubSubSubscriptionQueue
        from karpenter_tpu.controllers.errors import is_retryable

        queue = PubSubSubscriptionQueue(
            self.SUB_ID, self.MetricsAPI(err=RuntimeError("deadline"))
        )
        with pytest.raises(Exception) as excinfo:
            queue.length()
        assert is_retryable(excinfo.value)

    def test_invalid_subscription_id_rejected(self):
        from karpenter_tpu.cloudprovider.tpu import (
            PubSubSubscriptionQueue,
            parse_subscription_id,
        )

        with pytest.raises(ValueError):
            parse_subscription_id("not-a-subscription")
        with pytest.raises(ValueError):
            PubSubSubscriptionQueue("projects/p/topics/t", self.MetricsAPI())

    def test_factory_dispatch_and_validator(self):
        from karpenter_tpu.api.metricsproducer import (
            QueueSpec,
            validate_queue,
        )
        from karpenter_tpu.cloudprovider.tpu import (
            GCP_PUBSUB_SUBSCRIPTION,
            PubSubSubscriptionQueue,
            TPUFactory,
        )

        factory = TPUFactory(pubsub_api=self.MetricsAPI(undelivered=3))
        spec = QueueSpec(type=GCP_PUBSUB_SUBSCRIPTION, id=self.SUB_ID)
        queue = factory.queue_for(spec)
        assert isinstance(queue, PubSubSubscriptionQueue)
        assert queue.length() == 3
        validate_queue(spec)  # registered validator accepts
        with pytest.raises(ValueError):
            validate_queue(
                QueueSpec(type=GCP_PUBSUB_SUBSCRIPTION, id="bogus")
            )

    def test_queue_producer_end_to_end(self):
        """A queue MetricsProducer over a Pub/Sub subscription updates
        status + both gauges through the runtime — the reference's SQS
        suite flow (queue/producer.go:30-57) on the GCP provider."""
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.metricsproducer import (
            MetricsProducer,
            MetricsProducerSpec,
            QueueSpec,
        )
        from karpenter_tpu.cloudprovider.tpu import (
            GCP_PUBSUB_SUBSCRIPTION,
            TPUFactory,
        )
        from karpenter_tpu.runtime import KarpenterRuntime

        factory = TPUFactory(
            pubsub_api=self.MetricsAPI(undelivered=41, age=99)
        )
        runtime = KarpenterRuntime(cloud_provider_factory=factory)
        runtime.store.create(
            MetricsProducer(
                metadata=ObjectMeta(name="work"),
                spec=MetricsProducerSpec(
                    queue=QueueSpec(
                        type=GCP_PUBSUB_SUBSCRIPTION, id=self.SUB_ID
                    )
                ),
            )
        )
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "work")
        assert mp.status.queue.length == 41
        assert mp.status.queue.oldest_message_age_seconds == 99
