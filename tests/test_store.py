"""Object store semantics: CRUD isolation, watches, indexes, scale subresource."""

import pytest

from karpenter_tpu.api import Node, Pod, ScalableNodeGroup
from karpenter_tpu.api.core import ObjectMeta, PodSpec
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroupSpec
from karpenter_tpu.store import ConflictError, NotFoundError, Store


def sng(name="group", namespace="default", replicas=None):
    return ScalableNodeGroup(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=ScalableNodeGroupSpec(replicas=replicas, type="FakeNodeGroup", id=name),
    )


class TestCrud:
    def test_create_get_roundtrip(self):
        store = Store()
        created = store.create(sng(replicas=3))
        assert created.metadata.resource_version == 1
        assert created.metadata.uid
        got = store.get("ScalableNodeGroup", "default", "group")
        assert got.spec.replicas == 3

    def test_deepcopy_isolation(self):
        store = Store()
        obj = sng(replicas=3)
        store.create(obj)
        obj.spec.replicas = 99  # caller's mutation must not leak in
        assert store.get("ScalableNodeGroup", "default", "group").spec.replicas == 3
        got = store.get("ScalableNodeGroup", "default", "group")
        got.spec.replicas = 77  # reader's mutation must not leak in
        assert store.get("ScalableNodeGroup", "default", "group").spec.replicas == 3

    def test_create_conflict(self):
        store = Store()
        store.create(sng())
        with pytest.raises(ConflictError):
            store.create(sng())

    def test_get_missing(self):
        store = Store()
        with pytest.raises(NotFoundError):
            store.get("ScalableNodeGroup", "default", "nope")
        assert store.try_get("ScalableNodeGroup", "default", "nope") is None

    def test_stale_update_rejected(self):
        store = Store()
        stale = store.create(sng(replicas=1))
        fresh = store.get("ScalableNodeGroup", "default", "group")
        fresh.spec.replicas = 7
        store.update(fresh)
        stale.spec.replicas = 99
        with pytest.raises(ConflictError):
            store.update(stale)  # must not clobber the concurrent write
        assert store.get("ScalableNodeGroup", "default", "group").spec.replicas == 7

    def test_update_bumps_rv_preserves_identity(self):
        store = Store()
        created = store.create(sng(replicas=1))
        # create/update stamp identity on the CALLER's object (like
        # controller-runtime), so capture the pre-update rv for comparison
        uid, rv0 = created.metadata.uid, created.metadata.resource_version
        created.spec.replicas = 5
        updated = store.update(created)
        assert updated.spec.replicas == 5
        assert updated.metadata.uid == uid
        assert updated.metadata.resource_version > rv0
        # the caller's mutations after update never reach the store
        updated.spec.replicas = 99
        assert (
            store.get("ScalableNodeGroup", "default", "group").spec.replicas
            == 5
        )

    def test_patch_status_does_not_clobber_spec(self):
        store = Store()
        stale = store.create(sng(replicas=1))
        # another actor updates spec.replicas to 7
        fresh = store.get("ScalableNodeGroup", "default", "group")
        fresh.spec.replicas = 7
        store.update(fresh)
        # status patch from the stale copy must keep the new spec
        stale.status.replicas = 1
        store.patch_status(stale)
        after = store.get("ScalableNodeGroup", "default", "group")
        assert after.spec.replicas == 7
        assert after.status.replicas == 1

    def test_delete(self):
        store = Store()
        store.create(sng())
        store.delete("ScalableNodeGroup", "default", "group")
        with pytest.raises(NotFoundError):
            store.get("ScalableNodeGroup", "default", "group")

    def test_list_filters(self):
        store = Store()
        n1 = Node(metadata=ObjectMeta(name="a", labels={"group": "x"}))
        n2 = Node(metadata=ObjectMeta(name="b", labels={"group": "y"}))
        store.create(n1)
        store.create(n2)
        assert len(store.list("Node")) == 2
        assert [n.metadata.name for n in store.list("Node", label_selector={"group": "x"})] == ["a"]


class TestKindIndex:
    def test_list_never_scans_other_kinds(self):
        """Listing an absent kind over a large store is O(1) — the
        per-kind index, not a full scan (r3: listing zero Namespaces
        used to walk every pod)."""
        import time

        from karpenter_tpu.api.core import ObjectMeta, Pod, PodSpec

        store = Store()
        for i in range(20_000):
            store.create(
                Pod(metadata=ObjectMeta(name=f"p{i}"), spec=PodSpec())
            )
        t0 = time.perf_counter()
        assert store.list("Namespace") == []
        assert (time.perf_counter() - t0) * 1e3 < 5.0

    def test_update_keeps_list_order(self):
        """A status write must not move the object to the end of the
        kind order — the oracle encoder's row order (and with it solver
        tie-breaks) rides list() order (r3 code review)."""
        from karpenter_tpu.api.core import ObjectMeta, Pod, PodSpec

        store = Store()
        for name in ("a", "b", "c"):
            store.create(
                Pod(metadata=ObjectMeta(name=name), spec=PodSpec())
            )
        middle = store.get("Pod", "default", "b")
        store.update(middle)
        assert [
            p.metadata.name for p in store.list("Pod")
        ] == ["a", "b", "c"]
        # external watch echoes keep position too
        from karpenter_tpu.store.store import MODIFIED

        echo = store.get("Pod", "default", "a")
        echo.metadata.resource_version = "external-rv"
        store.apply_event(MODIFIED, echo)
        assert [
            p.metadata.name for p in store.list("Pod")
        ] == ["a", "b", "c"]


class TestPodIndex:
    def test_pods_on_node(self):
        store = Store()
        store.create(Pod(metadata=ObjectMeta(name="p1"), spec=PodSpec(node_name="n1")))
        store.create(Pod(metadata=ObjectMeta(name="p2"), spec=PodSpec(node_name="n1")))
        store.create(Pod(metadata=ObjectMeta(name="p3"), spec=PodSpec(node_name="n2")))
        store.create(Pod(metadata=ObjectMeta(name="pending"), spec=PodSpec()))
        assert {p.metadata.name for p in store.pods_on_node("n1")} == {"p1", "p2"}
        assert len(store.pods_on_node("n2")) == 1
        store.delete("Pod", "default", "p1")
        assert {p.metadata.name for p in store.pods_on_node("n1")} == {"p2"}

    def test_index_entries_freed_on_node_drain(self):
        store = Store()
        pod = store.create(
            Pod(metadata=ObjectMeta(name="p"), spec=PodSpec(node_name="n1"))
        )
        store.delete(pod)
        assert "n1" not in store._pods_by_node  # no unbounded growth on churn

    def test_index_follows_node_reassignment(self):
        store = Store()
        pod = store.create(
            Pod(metadata=ObjectMeta(name="p"), spec=PodSpec(node_name="n1"))
        )
        pod.spec.node_name = "n2"
        store.update(pod)
        assert store.pods_on_node("n1") == []
        assert len(store.pods_on_node("n2")) == 1


class TestWatch:
    def test_watch_events(self):
        store = Store()
        events = []
        store.watch("ScalableNodeGroup", lambda e, o: events.append((e, o.metadata.name)))
        obj = store.create(sng())
        obj.spec.replicas = 2
        store.update(obj)
        store.delete(obj)
        assert events == [
            ("Added", "group"),
            ("Modified", "group"),
            ("Deleted", "group"),
        ]

    def test_watch_kind_filter(self):
        store = Store()
        events = []
        store.watch("Node", lambda e, o: events.append(e))
        store.create(sng())
        assert events == []


class TestScaleSubresource:
    """reference: scalablenodegroup.go:51 + autoscaler.go:196-221"""

    def test_get_scale(self):
        store = Store()
        obj = sng(replicas=4)
        obj.status.replicas = 2
        store.create(obj)
        scale = store.get_scale("ScalableNodeGroup", "default", "group")
        assert scale.spec_replicas == 4
        assert scale.status_replicas == 2

    def test_update_scale_fires_watch(self):
        store = Store()
        store.create(sng(replicas=1))
        events = []
        store.watch("ScalableNodeGroup", lambda e, o: events.append(o.spec.replicas))
        scale = store.get_scale("ScalableNodeGroup", "default", "group")
        scale.spec_replicas = 9
        store.update_scale("ScalableNodeGroup", scale)
        assert store.get("ScalableNodeGroup", "default", "group").spec.replicas == 9
        assert events == [9]  # watch-driven actuation path

    def test_unregistered_kind(self):
        store = Store()
        with pytest.raises(NotFoundError):
            store.get_scale("HorizontalAutoscaler", "default", "x")


class TestIncarnationIdentity:
    def test_recreate_mints_fresh_uid(self):
        """create() stamps identity on the caller's object; re-creating
        with a retained (already-stamped) object after a delete must mint
        a NEW incarnation — uid distinguishes delete+recreate from update
        (the k8s uid contract)."""
        store = Store()
        obj = store.create(sng(replicas=1))
        first_uid = obj.metadata.uid
        first_created = obj.metadata.creation_timestamp
        assert first_uid
        store.delete("ScalableNodeGroup", "default", "group")
        recreated = store.create(obj)  # same retained instance
        assert recreated.metadata.uid
        assert recreated.metadata.uid != first_uid
        assert recreated.metadata.creation_timestamp >= first_created
