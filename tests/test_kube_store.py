"""Real-cluster mode: KubeStore against a protocol-faithful fake apiserver.

The reference's controllers run against a real apiserver via client-go
informers (reference: pkg/controllers/manager.go; tests boot envtest,
pkg/test/environment/local.go). These tests drive KubeClient/KubeStore —
list+watch mirror, REST writes, merge-patch status, scale subresource,
coordination leases — over actual HTTP against tests/fake_apiserver.py,
then run the WHOLE control plane (KarpenterRuntime) on top of it.
"""

import os
import time

import pytest

from karpenter_tpu.api import ScalableNodeGroup
from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroupSpec
from karpenter_tpu.leaderelection import LeaderElector
from karpenter_tpu.store import ConflictError, Scale
from karpenter_tpu.store.kube import KubeClient, KubeStore
from tests.fake_apiserver import FakeApiServer


@pytest.fixture()
def api():
    server = FakeApiServer()
    server.start()
    yield server
    server.stop()


@pytest.fixture()
def kube(api):
    client = KubeClient(base_url=api.url, timeout=5.0)
    store = KubeStore(client, resync_backoff=0.05)
    yield store
    store.close()


def sng(name="group", replicas=None):
    return ScalableNodeGroup(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=ScalableNodeGroupSpec(
            replicas=replicas, type="FakeNodeGroup", id=name
        ),
    )


def wait_for(predicate, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


class TestCrud:
    def test_create_stamps_callers_object(self, kube):
        """Same contract as the local Store (store.py create/update): the
        caller's object gets the server-assigned identity in place, so
        return-value-ignoring code behaves identically on both stores."""
        obj = sng(replicas=3, name="stamped")
        kube.create(obj)
        assert obj.metadata.uid
        assert obj.metadata.resource_version
        obj.spec.replicas = 4
        kube.update(obj)  # carries the stamped rv: no conflict

    def test_create_echoes_into_mirror(self, kube):
        created = kube.create(sng(replicas=3))
        assert created.metadata.resource_version > 0
        assert wait_for(
            lambda: kube.try_get("ScalableNodeGroup", "default", "group")
            is not None
        )
        got = kube.get("ScalableNodeGroup", "default", "group")
        assert got.spec.replicas == 3

    def test_update_with_stale_rv_conflicts(self, kube):
        created = kube.create(sng(replicas=1))
        fresh = kube.client.get("ScalableNodeGroup", "default", "group")
        fresh.spec.replicas = 5
        kube.update(fresh)
        created.spec.replicas = 9
        with pytest.raises(ConflictError):
            kube.update(created)  # stale resourceVersion must lose

    def test_patch_status_is_merge_patch(self, api, kube):
        kube.create(sng(replicas=2))
        obj = kube.client.get("ScalableNodeGroup", "default", "group")
        obj.status.replicas = 2
        kube.patch_status(obj)
        doc = next(
            d for d in api.objects("scalablenodegroups")
            if d["metadata"]["name"] == "group"
        )
        assert doc["status"]["replicas"] == 2
        assert doc["spec"]["replicas"] == 2  # spec untouched by status patch

    def test_patch_status_deletes_vanished_map_keys(self, api, kube):
        """merge-patch only sets keys, so a reservedCapacity resource entry
        removed locally used to linger upstream forever; the store now
        nulls keys the mirror saw upstream but the local object dropped
        (RFC 7386 deletion)."""
        from karpenter_tpu.api.metricsproducer import (
            MetricsProducer,
            MetricsProducerSpec,
            ReservedCapacitySpec,
        )

        kube.create(
            MetricsProducer(
                metadata=ObjectMeta(name="mp", namespace="default"),
                spec=MetricsProducerSpec(
                    reserved_capacity=ReservedCapacitySpec(
                        node_selector={"group": "a"}
                    )
                ),
            )
        )
        obj = kube.client.get("MetricsProducer", "default", "mp")
        obj.status.reserved_capacity = {
            "cpu": "10.00%, 1/10",
            "memory": "5.00%, 1Gi/20Gi",
        }
        kube.patch_status(obj)
        assert wait_for(
            lambda: "memory"
            in (
                (m := kube.try_get("MetricsProducer", "default", "mp"))
                and m.status.reserved_capacity
                or {}
            )
        )
        obj = kube.client.get("MetricsProducer", "default", "mp")
        obj.status.reserved_capacity = {"cpu": "20.00%, 2/10"}
        kube.patch_status(obj)
        doc = next(
            d
            for d in api.objects("metricsproducers")
            if d["metadata"]["name"] == "mp"
        )
        assert doc["status"]["reservedCapacity"] == {"cpu": "20.00%, 2/10"}

    def test_opaque_string_resource_version_survives_decode(self):
        """k8s resourceVersions are opaque strings per the API conventions;
        a non-numeric rv must decode (mirror only needs equality), not
        kill the informer path with int()."""
        from karpenter_tpu.store.kube import decode_from_read
        from karpenter_tpu.store.store import ADDED, MODIFIED
        from karpenter_tpu.store.store import Store as LocalStore

        doc = {
            "apiVersion": "autoscaling.karpenter.sh/v1alpha1",
            "kind": "ScalableNodeGroup",
            "metadata": {
                "name": "g",
                "namespace": "default",
                "resourceVersion": "0x1f-opaque",
            },
            "spec": {"type": "FakeNodeGroup", "id": "g"},
        }
        obj = decode_from_read(doc)
        assert obj.metadata.resource_version == "0x1f-opaque"
        mirror = LocalStore()
        mirror.apply_event(ADDED, obj)  # must not raise on max()
        echo = decode_from_read(doc)
        mirror.apply_event(MODIFIED, echo)  # equality dedup still works
        assert (
            mirror.get(
                "ScalableNodeGroup", "default", "g"
            ).metadata.resource_version
            == "0x1f-opaque"
        )

    def test_delete_and_watch_removal(self, kube):
        kube.create(sng())
        assert wait_for(
            lambda: kube.try_get("ScalableNodeGroup", "default", "group")
        )
        kube.delete("ScalableNodeGroup", "default", "group")
        assert wait_for(
            lambda: kube.try_get("ScalableNodeGroup", "default", "group")
            is None
        )

    def test_external_writer_visible_through_watch(self, api, kube):
        """Objects created by OTHER clients (kubectl) arrive via watch."""
        api.put_object(
            "scalablenodegroups",
            {
                "apiVersion": "autoscaling.karpenter.sh/v1alpha1",
                "kind": "ScalableNodeGroup",
                "metadata": {"name": "external"},
                "spec": {"type": "FakeNodeGroup", "id": "external"},
            },
        )
        assert wait_for(
            lambda: kube.try_get("ScalableNodeGroup", "default", "external")
            is not None
        )

    def test_scale_subresource(self, kube):
        kube.create(sng(replicas=2))
        scale = kube.get_scale("ScalableNodeGroup", "default", "group")
        assert scale.spec_replicas == 2
        kube.update_scale(
            "ScalableNodeGroup",
            Scale(
                namespace="default", name="group",
                spec_replicas=7, status_replicas=2,
            ),
        )
        assert wait_for(
            lambda: (
                kube.try_get("ScalableNodeGroup", "default", "group") or sng()
            ).spec.replicas == 7
        )

    def test_real_apiserver_pod_dialect_decodes(self, api, kube):
        """Real pods carry fields we don't model + resources.requests
        nesting; the mirror must decode leniently and keep the requests."""
        api.put_object(
            "pods",
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": "real",
                    "creationTimestamp": "2026-07-29T12:00:00Z",
                    "managedFields": [{"manager": "kubelet"}],
                },
                "spec": {
                    "schedulerName": "default-scheduler",
                    "containers": [
                        {
                            "name": "app",
                            "image": "nginx",
                            "resources": {
                                "requests": {"cpu": "250m", "memory": "1Gi"}
                            },
                        }
                    ],
                },
                "status": {"phase": "Pending", "qosClass": "Burstable"},
            },
        )
        assert wait_for(
            lambda: kube.try_get("Pod", "default", "real") is not None
        )
        pod = kube.get("Pod", "default", "real")
        assert pod.requests()["cpu"].to_float() == pytest.approx(0.25)
        assert pod.metadata.creation_timestamp > 1.7e9

    def test_real_apiserver_pod_scheduling_fields_decode(self, api, kube):
        """Affinity (required + preferred), init containers and overhead
        survive the lenient apiserver decode — real scheduler-shaped pods
        feed the solver with full constraint fidelity."""
        api.put_object(
            "pods",
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {"name": "constrained"},
                "spec": {
                    "schedulerName": "default-scheduler",
                    "containers": [
                        {
                            "name": "app",
                            "resources": {"requests": {"cpu": "250m"}},
                        }
                    ],
                    "initContainers": [
                        {
                            "name": "init",
                            "resources": {"requests": {"cpu": "2"}},
                        }
                    ],
                    "overhead": {"memory": "64Mi"},
                    "affinity": {
                        "nodeAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "nodeSelectorTerms": [
                                    {
                                        "matchExpressions": [
                                            {
                                                "key": "zone",
                                                "operator": "NotIn",
                                                "values": ["z9"],
                                            }
                                        ]
                                    }
                                ]
                            },
                            "preferredDuringSchedulingIgnoredDuringExecution": [
                                {
                                    "weight": 50,
                                    "preference": {
                                        "matchExpressions": [
                                            {
                                                "key": "disk",
                                                "operator": "Exists",
                                            }
                                        ]
                                    },
                                }
                            ],
                        }
                    },
                },
                "status": {"phase": "Pending"},
            },
        )
        assert wait_for(
            lambda: kube.try_get("Pod", "default", "constrained") is not None
        )
        pod = kube.get("Pod", "default", "constrained")
        from karpenter_tpu.api.core import (
            affinity_shape,
            preference_score,
            preferred_shape,
        )

        assert pod.effective_requests()["cpu"].to_float() == pytest.approx(2)
        assert affinity_shape(pod.spec.affinity) == (
            (("zone", "NotIn", ("z9",)),),
        )
        assert (
            preference_score(
                {"disk": "ssd"}, preferred_shape(pod.spec.affinity)
            )
            == 50
        )


class TestOccupancyOnKube:
    def test_census_fed_by_http_watch(self, api, kube):
        """Existing-pod occupancy over the REAL-cluster path: a bound
        replica arriving through the apiserver watch spends its zone,
        and the pending-pods solve routes the next replica elsewhere —
        the ScheduledOccupancy adoption + watch contract certified
        against HTTP, not just the in-memory store."""
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
            solve_pending,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.columnar import PendingFeed

        zone = "topology.kubernetes.io/zone"
        for z in ("a", "b"):
            api.put_object(
                "nodes",
                {
                    "apiVersion": "v1",
                    "kind": "Node",
                    "metadata": {
                        "name": f"n-{z}",
                        "labels": {"group": z, zone: f"us-{z}"},
                    },
                    "status": {
                        "allocatable": {
                            "cpu": "64",
                            "memory": "64Gi",
                            "pods": "110",
                        },
                        "conditions": [
                            {"type": "Ready", "status": "True"}
                        ],
                    },
                },
            )
            api.put_object(
                "metricsproducers",
                {
                    "apiVersion": "autoscaling.karpenter.sh/v1alpha1",
                    "kind": "MetricsProducer",
                    "metadata": {"name": f"group-{z}"},
                    "spec": {
                        "pendingCapacity": {"nodeSelector": {"group": z}}
                    },
                },
            )

        def pod_doc(name, bound_to=None):
            return {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": name,
                    "labels": {"app": "db"},
                },
                "spec": {
                    **({"nodeName": bound_to} if bound_to else {}),
                    "containers": [
                        {
                            "name": "c",
                            "resources": {
                                "requests": {
                                    "cpu": "1",
                                    "memory": "1Gi",
                                }
                            },
                        }
                    ],
                    "affinity": {
                        "podAntiAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": [
                                {
                                    "labelSelector": {
                                        "matchLabels": {"app": "db"}
                                    },
                                    "topologyKey": zone,
                                }
                            ]
                        }
                    },
                },
                "status": {
                    "phase": "Running" if bound_to else "Pending"
                },
            }

        api.put_object("pods", pod_doc("db-live", bound_to="n-a"))
        api.put_object("pods", pod_doc("db-pending"))

        feed = PendingFeed(kube, group_profile)
        assert wait_for(lambda: len(feed.pods) == 1)
        assert wait_for(lambda: feed.occupancy.generation >= 1)
        # each kind rides its own watch stream: synchronize on ALL the
        # mirrors the solve reads, not just the pod arena
        assert wait_for(lambda: len(kube.list("MetricsProducer")) == 2)
        assert wait_for(lambda: len(feed.nodes.nodes()) == 2)

        mps = [
            mp
            for mp in kube.list("MetricsProducer")
            if mp.spec.pending_capacity is not None
        ]
        assert len(mps) == 2
        solve_pending(kube, mps, GaugeRegistry(), feed=feed)
        by_name = {
            mp.metadata.name: mp.status.pending_capacity for mp in mps
        }
        # zone a is spent by db-live (seen over HTTP): the pending
        # replica lands in b
        assert by_name["group-a"].pending_pods == 0
        assert by_name["group-b"].pending_pods == 1
        assert by_name["group-b"].unschedulable_pods == 0


class TestDialect:
    def test_strict_manifests_still_reject_resources_nesting(self):
        """Only the apiserver-read (lenient) path accepts the core/v1
        `resources` nesting; user manifests keep the hard error so limits
        are never silently dropped."""
        from karpenter_tpu.api.serialization import from_manifest

        doc = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": "p"},
            "spec": {
                "containers": [
                    {"resources": {"requests": {"cpu": "1"}}}
                ]
            },
        }
        with pytest.raises(ValueError, match="resources"):
            from_manifest(doc)
        pod = from_manifest(doc, lenient=True)
        assert pod.requests()["cpu"].to_float() == 1.0

    def test_resync_echo_does_not_spam_watchers(self, kube):
        """apply_event must drop relist echoes of unchanged objects, or
        every reconnect re-notifies the whole fleet into the feed."""
        kube.create(sng(replicas=1))
        assert wait_for(
            lambda: kube.try_get("ScalableNodeGroup", "default", "group")
        )
        events = []
        kube.watch("ScalableNodeGroup", lambda ev, o: events.append(ev))
        kube._resync("ScalableNodeGroup")  # same rv: no notification
        assert events == []
        kube._resync("ScalableNodeGroup")
        assert events == []


class TestLease:
    def test_leader_election_over_coordination_api(self, kube):
        clock = lambda: 5000.0
        elector = LeaderElector(kube, identity="me", clock=clock)
        assert elector.try_acquire()
        lease = kube.get("Lease", "kube-system", "karpenter-leader")
        assert lease.holder == "me"
        other = LeaderElector(kube, identity="rival", clock=clock)
        assert not other.try_acquire()  # lease held and fresh

    def test_lease_takeover_after_expiry(self, kube):
        t = {"now": 5000.0}
        elector = LeaderElector(kube, identity="a", clock=lambda: t["now"])
        assert elector.try_acquire()
        t["now"] += 1000  # way past lease_duration
        rival = LeaderElector(kube, identity="b", clock=lambda: t["now"])
        assert rival.try_acquire()
        assert kube.get("Lease", "kube-system", "karpenter-leader").holder == "b"


class TestControlPlaneOnKube:
    def test_runtime_converges_through_real_http(self, api, kube):
        """The whole control plane (manager + controllers + feed) running
        against the apiserver protocol: an SNG actuates through the fake
        provider and its status lands back on the apiserver."""
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime

        provider = FakeFactory()
        provider.node_replicas["group"] = 5
        clock = {"t": 1000.0}
        runtime = KarpenterRuntime(
            store=kube,
            cloud_provider_factory=provider,
            clock=lambda: clock["t"],
        )
        kube.create(sng(replicas=3))
        assert wait_for(
            lambda: kube.try_get("ScalableNodeGroup", "default", "group")
            is not None
        )
        runtime.manager.reconcile_all()
        # status + conditions written via merge-patch /status
        def happy():
            doc = [
                d for d in api.objects("scalablenodegroups")
                if d["metadata"]["name"] == "group"
            ]
            if not doc:
                return False
            conditions = doc[0].get("status", {}).get("conditions", [])
            return any(
                c["type"] == "Active" and c["status"] == "True"
                for c in conditions
            )
        clock["t"] += 61
        runtime.manager.reconcile_all()
        assert wait_for(happy), api.objects("scalablenodegroups")
        runtime.close()


class TestChunkedList:
    def test_relist_pages_through_continue_tokens(self, api):
        """The mirror's relist uses limit+continue chunking (one giant
        LIST at 100k pods would spike memory on both ends); all pages
        must be gathered and the first page's collection rv kept."""
        from karpenter_tpu.store.kube import KubeClient

        for i in range(23):
            api.put_object(
                "pods",
                {
                    "apiVersion": "v1",
                    "kind": "Pod",
                    "metadata": {"name": f"p{i:02}"},
                    "spec": {"containers": [{"requests": {"cpu": "1"}}]},
                },
            )
        client = KubeClient(base_url=api.url)
        client.list_chunk_size = 10
        before = api.list_pages_served
        objs, rv = client.list("Pod")
        assert len(objs) == 23
        assert sorted(o.metadata.name for o in objs) == [
            f"p{i:02}" for i in range(23)
        ]
        assert api.list_pages_served - before == 3  # 10 + 10 + 3
        assert rv and rv != "0"


class TestArbitraryScaleTargetOnKube:
    """Discovery-based scale-target resolution (reference:
    autoscaler.go:196-237 — GVK->GVR via RESTMapper over discovery).
    Kinds outside the static RESOURCES table resolve through /apis."""

    def deployment_doc(self, name="web", replicas=5):
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"replicas": replicas},
            "status": {"replicas": replicas},
        }

    def test_scale_via_explicit_api_version(self, api, kube):
        api.put_object("deployments", self.deployment_doc())
        scale = kube.get_scale(
            "Deployment", "default", "web", api_version="apps/v1"
        )
        assert (scale.spec_replicas, scale.status_replicas) == (5, 5)
        scale.spec_replicas = 9
        kube.update_scale("Deployment", scale, api_version="apps/v1")
        (doc,) = [
            d for d in api.objects("deployments")
            if d["metadata"]["name"] == "web"
        ]
        assert doc["spec"]["replicas"] == 9

    def test_discovery_without_api_version_walks_groups(self, api):
        client = KubeClient(base_url=api.url, timeout=5.0)
        assert client.resolve_kind("Deployment") == (
            "apis/apps/v1", "deployments", True
        )

    def test_unknown_kind_reports_not_served(self, api):
        client = KubeClient(base_url=api.url, timeout=5.0)
        with pytest.raises(Exception, match="not served"):
            client.resolve_kind("FlumeJob", "flume.example.com/v9")

    def test_ha_targeting_deployment_converges(self, api, kube):
        """The whole control plane over HTTP: an HA whose scaleTargetRef
        names a Deployment (apps/v1) resolves via discovery and actuates
        through PUT .../deployments/web/scale."""
        from karpenter_tpu.api.core import ObjectMeta as Meta
        from karpenter_tpu.api.horizontalautoscaler import (
            CrossVersionObjectReference,
            HorizontalAutoscaler,
            HorizontalAutoscalerSpec,
            Metric,
            MetricTarget,
            PrometheusMetricSource,
        )
        from karpenter_tpu.runtime import KarpenterRuntime

        api.put_object("deployments", self.deployment_doc())
        runtime = KarpenterRuntime(store=kube)
        gauge = runtime.registry.register(
            "reserved_capacity", "cpu_utilization"
        )
        gauge.set("web", "default", 0.85)
        kube.create(
            HorizontalAutoscaler(
                metadata=Meta(name="web", namespace="default"),
                spec=HorizontalAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        api_version="apps/v1", kind="Deployment", name="web"
                    ),
                    min_replicas=1,
                    max_replicas=23,
                    metrics=[
                        Metric(
                            prometheus=PrometheusMetricSource(
                                query=(
                                    "karpenter_reserved_capacity_cpu_"
                                    'utilization{name="web"}'
                                ),
                                target=MetricTarget(
                                    type="Utilization", value=60
                                ),
                            )
                        )
                    ],
                ),
            )
        )
        assert wait_for(
            lambda: kube.try_get("HorizontalAutoscaler", "default", "web")
            is not None
        )
        runtime.manager.reconcile_all()

        def scaled():
            docs = [
                d for d in api.objects("deployments")
                if d["metadata"]["name"] == "web"
            ]
            return docs and docs[0]["spec"]["replicas"] == 8

        assert wait_for(scaled), api.objects("deployments")
        runtime.close()

    def test_same_kind_across_groups_resolves_per_api_version(self, api):
        """Two CRDs may share a kind across API groups; resolution (and
        the memo) must key on (kind, apiVersion), not kind alone."""
        from tests import fake_apiserver as f

        f.API_GROUPS.setdefault("b.example.com", ["v1"])
        f.API_RESOURCES["apis/b.example.com/v1"] = [
            ("widgets", "Deployment", True)
        ]
        try:
            client = KubeClient(base_url=api.url, timeout=5.0)
            assert client.resolve_kind("Deployment", "apps/v1") == (
                "apis/apps/v1", "deployments", True
            )
            # the apps/v1 answer must not be served for b.example.com/v1
            assert client.resolve_kind(
                "Deployment", "b.example.com/v1"
            ) == ("apis/b.example.com/v1", "widgets", True)
        finally:
            f.API_GROUPS.pop("b.example.com", None)
            f.API_RESOURCES.pop("apis/b.example.com/v1", None)

    def test_blind_walk_tolerates_broken_group(self):
        """A stale APIService (503 on its APIResourceList) must not
        poison blind resolution of a kind served by a healthy group —
        the RESTMapper's partial-discovery posture. The broken group is
        walked FIRST, so only the skip keeps resolution alive; with an
        EXPLICIT apiVersion naming the broken group, the failure must
        surface instead."""
        client = KubeClient(base_url="http://127.0.0.1:1", timeout=1.0)

        def fake_request(method, path, *args, **kwargs):
            if path == "apis":
                return {
                    "groups": [
                        {
                            "name": "broken.example.com",
                            "preferredVersion": {
                                "groupVersion": "broken.example.com/v1"
                            },
                            "versions": [
                                {"groupVersion": "broken.example.com/v1"}
                            ],
                        },
                        {
                            "name": "apps",
                            "preferredVersion": {
                                "groupVersion": "apps/v1"
                            },
                            "versions": [{"groupVersion": "apps/v1"}],
                        },
                    ]
                }
            if path == "api/v1":
                return {"resources": []}
            if path == "apis/broken.example.com/v1":
                raise RuntimeError("GET: 503 service unavailable")
            if path == "apis/apps/v1":
                return {
                    "resources": [
                        {
                            "name": "deployments",
                            "kind": "Deployment",
                            "namespaced": True,
                        }
                    ]
                }
            raise AssertionError(f"unexpected discovery GET {path}")

        client._request = fake_request
        assert client.resolve_kind("Deployment") == (
            "apis/apps/v1", "deployments", True
        )
        with pytest.raises(RuntimeError, match="503"):
            client.resolve_kind("Widget", "broken.example.com/v1")


class TestDiscoveryFuzz:
    """Property sweep over randomized discovery documents: resolve_kind
    must honor (kind, apiVersion) addressing, preferred-version order,
    and partial-discovery tolerance for ANY served layout."""

    def _client_for(self, groups, broken):
        """groups: {group: {version: [(plural, kind, namespaced)]}};
        broken: set of 'group/version' whose APIResourceList 500s."""
        client = KubeClient(base_url="http://127.0.0.1:1", timeout=1.0)

        def fake_request(method, path, *args, **kwargs):
            if path == "apis":
                return {
                    "groups": [
                        {
                            "name": g,
                            "preferredVersion": {
                                "groupVersion": f"{g}/{sorted(vs)[0]}"
                            },
                            "versions": [
                                {"groupVersion": f"{g}/{v}"}
                                for v in sorted(vs)
                            ],
                        }
                        for g, vs in groups.items()
                    ]
                }
            if path == "api/v1":
                return {"resources": []}
            assert path.startswith("apis/"), path
            gv = path[len("apis/"):]
            if gv in broken:
                raise RuntimeError(f"GET {path}: 503")
            g, _, v = gv.partition("/")
            entries = groups.get(g, {}).get(v)
            if entries is None:
                from karpenter_tpu.store import NotFoundError

                raise NotFoundError(f"GET {path}: 404")
            return {
                "resources": [
                    {"name": plural, "kind": kind, "namespaced": ns}
                    for plural, kind, ns in entries
                ]
            }

        client._request = fake_request
        return client

    @staticmethod
    def _walk_order(groups):
        """The exact group-version order _discovery_prefixes promises:
        /apis group order, preferred version (sorted(vs)[0] in the fake)
        first within each group."""
        order = []
        for group, versions in groups.items():
            ordered = sorted(versions)
            order.extend(f"{group}/{v}" for v in ordered)
        return order

    def test_fuzzed_layouts(self):
        import random

        from karpenter_tpu.store import NotFoundError

        rng = random.Random(7)
        kinds = ["Widget", "Gadget", "Sprocket", "Deployment"]
        for case in range(60):
            groups = {}
            broken = set()
            # kind -> {group/version: (plural, namespaced)}
            serving = {}
            for g in range(rng.randint(1, 4)):
                group = f"g{g}.example.com"
                versions = {}
                for v in range(rng.randint(1, 3)):
                    version = f"v{v + 1}"
                    entries = []
                    for kind in kinds:
                        if rng.random() < 0.3:
                            # irregular plurals and cluster-scoped kinds
                            # are both legal; the resolver must return
                            # the WIRE values, not conventions
                            plural = kind.lower() + rng.choice(
                                ["s", "es", "-irregular"]
                            )
                            namespaced = rng.random() < 0.5
                            entries.append((plural, kind, namespaced))
                            serving.setdefault(kind, {})[
                                f"{group}/{version}"
                            ] = (plural, namespaced)
                    versions[version] = entries
                    if rng.random() < 0.2:
                        broken.add(f"{group}/{version}")
                groups[group] = versions
            client = self._client_for(groups, broken)
            for kind in kinds:
                served = serving.get(kind, {})
                # explicit apiVersion: exact group-version addressing,
                # echoing the wire plural/namespaced values
                for gv, (plural, namespaced) in sorted(served.items()):
                    if gv in broken:
                        with pytest.raises(RuntimeError, match="503"):
                            client.resolve_kind(kind, gv)
                        continue
                    assert client.resolve_kind(kind, gv) == (
                        f"apis/{gv}", plural, namespaced
                    )
                # blind: the FIRST healthy serving group-version in the
                # documented walk order wins (not just any member)
                expected_gv = next(
                    (
                        gv
                        for gv in self._walk_order(groups)
                        if gv in served and gv not in broken
                    ),
                    None,
                )
                fresh = self._client_for(groups, broken)
                if expected_gv is not None:
                    plural, namespaced = served[expected_gv]
                    assert fresh.resolve_kind(kind) == (
                        f"apis/{expected_gv}", plural, namespaced
                    ), (case, kind)
                else:
                    with pytest.raises(NotFoundError):
                        fresh.resolve_kind(kind)

    def test_miss_is_negative_cached_with_ttl(self, monkeypatch):
        """A misconfigured scaleTargetRef must not re-walk the whole
        discovery surface every reconcile: misses cache for
        DISCOVERY_MISS_TTL, then retry (a late-installed CRD is picked
        up without a restart)."""
        from karpenter_tpu.store import NotFoundError
        from karpenter_tpu.store import kube as kube_mod

        client = KubeClient(base_url="http://127.0.0.1:1", timeout=1.0)
        calls = {"n": 0}
        resources = {"resources": []}

        def fake_request(method, path, *args, **kwargs):
            if path == "apis":
                calls["n"] += 1
                return {"groups": []}
            if path == "api/v1":
                return resources
            raise AssertionError(path)

        client._request = fake_request
        clock = {"t": 1000.0}
        monkeypatch.setattr(
            kube_mod.time, "monotonic", lambda: clock["t"]
        )
        with pytest.raises(NotFoundError):
            client.resolve_kind("Widget")
        assert calls["n"] == 1
        # within the TTL: no new walk, same typed error
        with pytest.raises(NotFoundError, match="cached"):
            client.resolve_kind("Widget")
        assert calls["n"] == 1
        # after the TTL: the walk retries; the now-served kind resolves
        # and clears the miss entry
        clock["t"] += kube_mod.DISCOVERY_MISS_TTL + 1
        resources["resources"] = [
            {"name": "widgets", "kind": "Widget", "namespaced": True}
        ]
        assert client.resolve_kind("Widget") == (
            "api/v1", "widgets", True
        )
        assert ("Widget", "") not in client._discovery_misses

    def test_degraded_walk_is_not_negative_cached(self, monkeypatch):
        """A blind walk that SKIPPED a broken group may have skipped
        exactly the serving one: the miss must NOT enter the negative
        cache, so the next reconcile retries immediately (the r5 review
        case: a momentary aggregated-API 503 must not become a 30 s
        resolution outage)."""
        from karpenter_tpu.store import NotFoundError

        client = KubeClient(base_url="http://127.0.0.1:1", timeout=1.0)
        state = {"healthy": False}

        def fake_request(method, path, *args, **kwargs):
            if path == "apis":
                return {
                    "groups": [
                        {
                            "name": "agg.example.com",
                            "preferredVersion": {
                                "groupVersion": "agg.example.com/v1"
                            },
                            "versions": [
                                {"groupVersion": "agg.example.com/v1"}
                            ],
                        }
                    ]
                }
            if path == "api/v1":
                return {"resources": []}
            assert path == "apis/agg.example.com/v1", path
            if not state["healthy"]:
                raise RuntimeError(f"GET {path}: 503")
            return {
                "resources": [
                    {"name": "widgets", "kind": "Widget", "namespaced": True}
                ]
            }

        client._request = fake_request
        with pytest.raises(NotFoundError, match="skipped"):
            client.resolve_kind("Widget")
        assert ("Widget", "") not in client._discovery_misses
        # the backend recovers: the VERY NEXT resolve succeeds (no TTL)
        state["healthy"] = True
        assert client.resolve_kind("Widget") == (
            "apis/agg.example.com/v1", "widgets", True
        )


@pytest.mark.skipif(
    not os.environ.get("KARPENTER_SCALE_TESTS"),
    reason="50k-object HTTP mirror; battletest sets KARPENTER_SCALE_TESTS=1",
)
class TestMirrorAtScale:
    def test_50k_pod_mirror_syncs_and_converges_after_churn(self, api):
        """The informer mirror at fleet scale over REAL HTTP: a 50k-pod
        initial sync pages through the continue protocol, and a churn
        slab (deletes + adds from another client) converges through the
        watch stream — the mirror equals server state afterward."""

        # seed server-side directly (the load is the protocol, not the
        # fake's put_object lock)
        with api._lock:
            for i in range(50_000):
                api._rv += 1
                doc = {
                    "apiVersion": "v1",
                    "kind": "Pod",
                    "metadata": {
                        "name": f"s{i:05}",
                        "namespace": "default",
                        "resourceVersion": str(api._rv),
                        "uid": f"uid-{i}",
                    },
                    "spec": {
                        "containers": [
                            {
                                "name": "main",
                                "resources": {
                                    "requests": {"cpu": "100m"}
                                },
                            }
                        ]
                    },
                }
                api._objects[("pods", "default", f"s{i:05}")] = doc
        client = KubeClient(base_url=api.url, timeout=30.0)
        store = KubeStore(
            client, watch_kinds=("Pod",), resync_backoff=0.1
        )
        try:
            assert wait_for(
                lambda: len(store.list("Pod")) == 50_000, timeout=60.0
            ), f"mirror stuck at {len(store.list('Pod'))}"
            # churn through the public protocol: 200 deletes + 200 adds
            for i in range(200):
                api.delete_object("pods", "default", f"s{i:05}")
                api.put_object(
                    "pods",
                    {
                        "apiVersion": "v1",
                        "kind": "Pod",
                        "metadata": {"name": f"c{i:03}"},
                        "spec": {
                            "containers": [
                                {
                                    "name": "main",
                                    "resources": {
                                        "requests": {"cpu": "50m"}
                                    },
                                }
                            ]
                        },
                    },
                )

            def converged():
                names = {
                    o.metadata.name for o in store.list("Pod")
                }
                return (
                    len(names) == 50_000
                    and "s00000" not in names
                    and "c000" in names
                    and "c199" in names
                )

            assert wait_for(converged, timeout=60.0)
        finally:
            store.close()
