"""Observability layer (karpenter_tpu/observability + registry histograms).

The acceptance pins (ISSUE 9 / docs/observability.md):

  * a `--simulate --trace-export` run emits valid Chrome-trace JSONL in
    which at least one coalesced solver dispatch span LINKS >= 2 request
    spans, reachable end to end from a tick-entry root to the SNG
    actuation span, and the run observes >= 1 end-to-end
    karpenter_reconcile_e2e_seconds sample;
  * a seeded chaos run produces a flight-recorder dump whose FSM-trip
    event backlinks the trace IDs of the degraded requests;
  * exposition conformance: promtool-style lint over expose_text()
    (TYPE lines, histogram bucket monotonicity, _sum/_count
    consistency, label escaping) and MetricsServer content-type/404;
  * /readyz reflects REAL state (503 in recovery warm-up / solver FSM
    tripped), /healthz stays liveness-only;
  * solver_trace probes jax.profiler ONCE and the unavailable path is
    allocation-free (the shared no-op);
  * tracing-enabled tick overhead stays bounded (the structural guard;
    `make bench-trace` publishes the honest <5% number).
"""

import json
import math
import os
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.observability import (
    FlightRecorder,
    MetricsServer,
    Tracer,
    default_flight_recorder,
    default_tracer,
    reset_default_flight_recorder,
    reset_default_tracer,
    set_default_flight_recorder,
    set_default_tracer,
)
from karpenter_tpu.observability import profiler as profiler_mod


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


@pytest.fixture
def fresh_tracer():
    """Isolated process-default tracer (instrumentation sites read the
    default dynamically)."""
    saved = default_tracer()
    tracer = reset_default_tracer()
    yield tracer
    set_default_tracer(saved)


@pytest.fixture
def fresh_recorder():
    saved = default_flight_recorder()
    recorder = reset_default_flight_recorder()
    yield recorder
    set_default_flight_recorder(saved)


# -- tracing core ------------------------------------------------------------


class TestTracer:
    def test_trace_mints_ids_and_spans_inherit(self):
        tracer = Tracer()
        with tracer.trace("tick") as root:
            with tracer.span("child") as child:
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
                with tracer.span("grandchild") as grand:
                    assert grand.parent_id == child.span_id
        with tracer.trace("tick") as root2:
            assert root2.trace_id != root.trace_id
        spans = tracer.snapshot()
        assert [s["name"] for s in spans] == [
            "grandchild", "child", "tick", "tick",
        ]

    def test_begin_close_crosses_threads(self):
        """A begin() span closed on another thread keeps its parent's
        trace id and never touches the worker's TLS stack."""
        tracer = Tracer()
        with tracer.trace("tick"):
            handle = tracer.begin("solver.request")
        done = threading.Event()

        def worker():
            with tracer.span(
                "solver.dispatch", parent=handle, links=[handle]
            ):
                handle.close(ok=True)
            done.set()

        threading.Thread(target=worker).start()
        assert done.wait(5)
        by_name = {s["name"]: s for s in tracer.snapshot()}
        request = by_name["solver.request"]
        dispatch = by_name["solver.dispatch"]
        assert request["trace"] == by_name["tick"]["trace"]
        assert dispatch["trace"] == request["trace"]
        assert dispatch["links"] == [request["id"]]

    def test_close_is_idempotent(self):
        tracer = Tracer()
        handle = tracer.begin("solver.request")
        handle.close()
        handle.close()
        assert len(tracer.snapshot()) == 1

    def test_disabled_tracer_is_allocation_free(self):
        tracer = Tracer()
        tracer.enabled = False
        first = tracer.trace("tick")
        second = tracer.span("child")
        assert first is second  # the shared no-op
        with first:
            pass
        assert tracer.begin("x") is None
        assert tracer.snapshot() == []

    def test_snapshot_limit_zero_returns_none(self):
        tracer = Tracer()
        tracer.begin("a").close()
        assert tracer.snapshot(limit=0) == []
        assert len(tracer.snapshot(limit=1)) == 1

    def test_ring_is_bounded_and_counts_drops(self):
        tracer = Tracer(capacity=4)
        for i in range(6):
            tracer.begin(f"s{i}").close()
        spans = tracer.snapshot()
        assert len(spans) == 4
        assert tracer.spans_total == 6
        assert tracer.spans_dropped == 2
        assert spans[-1]["name"] == "s5"

    def test_export_jsonl_valid_with_flow_links(self, tmp_path):
        tracer = Tracer()
        a = tracer.begin("req.a")
        b = tracer.begin("req.b")
        a.close()
        b.close()
        with tracer.span("dispatch", parent=a, links=[a, b]):
            pass
        path = str(tmp_path / "trace.jsonl")
        n = tracer.export_jsonl(path)
        lines = open(path).read().splitlines()
        assert len(lines) == n
        events = [json.loads(line) for line in lines]  # every line JSON
        complete = [e for e in events if e["ph"] == "X"]
        assert {"req.a", "req.b", "dispatch"} == {
            e["name"] for e in complete
        }
        dispatch = next(e for e in complete if e["name"] == "dispatch")
        assert len(dispatch["args"]["links"]) == 2
        # flow pairs render each link edge: one "s" at the linked span,
        # one "f" at the dispatch, per-edge ids (src>dst — two
        # dispatches linking one request must not share a flow id)
        starts = {e["id"] for e in events if e["ph"] == "s"}
        finishes = {e["id"] for e in events if e["ph"] == "f"}
        expected = {
            f"{sid}>{dispatch['id']}"
            for sid in dispatch["args"]["links"]
        }
        assert starts == finishes == expected

    def test_e2e_marks_feed_histogram(self):
        registry = GaugeRegistry()
        tracer = Tracer()
        tracer.bind_registry(registry)
        key = ("ScalableNodeGroup", "default", "grp")
        tracer.mark_observed(key)
        lead = tracer.ack_observed(key)
        assert lead is not None and lead >= 0.0
        hist = registry.gauge("reconcile", "e2e_seconds")
        assert hist.count("ScalableNodeGroup", "-") == 1
        # no mark -> no sample; drop retires a mark
        assert tracer.ack_observed(key) is None
        tracer.mark_observed(key)
        tracer.drop_observed(key)
        assert tracer.ack_observed(key) is None
        assert hist.count("ScalableNodeGroup", "-") == 1

    def test_e2e_mark_survives_renotification(self):
        """The engine's own status patches notify the watch path every
        reconcile: a pending mark must NOT be re-stamped (overwrite=
        False) or a multi-tick actuation measures ~one tick instead of
        event->ack."""
        clock = {"now": 100.0}
        tracer = Tracer(clock=lambda: clock["now"])
        key = ("ScalableNodeGroup", "default", "grp")
        tracer.mark_observed(key, overwrite=False)  # the real event
        for _ in range(5):  # deferring ticks, each with a self-patch
            clock["now"] += 10.0
            tracer.mark_observed(key, overwrite=False)
        lead = tracer.ack_observed(key)
        assert lead == pytest.approx(50.0)  # from the FIRST stamp

    def test_e2e_marks_noop_when_disabled(self):
        tracer = Tracer()
        tracer.enabled = False
        key = ("ScalableNodeGroup", "default", "grp")
        tracer.mark_observed(key)
        assert not tracer._observed  # hot path stays mark-free
        assert tracer.ack_observed(key) is None
        tracer.enabled = True
        tracer.mark_observed(key)
        tracer.enabled = False
        tracer.drop_observed(key)  # drop still clears a stale mark
        assert not tracer._observed


# -- flight recorder ---------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        recorder = FlightRecorder(capacity=3)
        for i in range(5):
            recorder.record("fault_injected", point=f"p{i}")
        events = recorder.events()
        assert len(events) == 3
        assert events[-1]["point"] == "p4"
        assert events[-1]["seq"] == 5

    def test_backlinks_current_trace(self, fresh_tracer):
        recorder = FlightRecorder()
        with fresh_tracer.trace("tick") as root:
            event = recorder.record("circuit_open", group="a/b")
        assert event["trace_ids"] == [root.trace_id]
        # explicit ids win
        event = recorder.record("fsm_trip", trace_ids=["t1", "t2"])
        assert event["trace_ids"] == ["t1", "t2"]

    def test_dump_is_crash_safe_and_pruned(self, tmp_path):
        recorder = FlightRecorder(
            dump_dir=str(tmp_path), keep_dumps=2
        )
        recorder.record("fault_injected", point="x")  # no auto-dump
        assert os.listdir(tmp_path) == []
        paths = [
            recorder.dump(reason=f"r{i}") for i in range(4)
        ]
        assert all(p is not None for p in paths)
        survivors = sorted(os.listdir(tmp_path))
        assert len(survivors) == 2  # pruned to keep_dumps
        assert not any(name.endswith(".tmp") for name in survivors)
        doc = json.load(open(os.path.join(tmp_path, survivors[-1])))
        assert doc["events"][0]["kind"] == "fault_injected"

    def test_keep_dumps_zero_keeps_nothing(self, tmp_path):
        """keep_dumps=0 must mean keep NONE, not keep all (dumps[:-0]
        would silently invert the bound)."""
        recorder = FlightRecorder(dump_dir=str(tmp_path), keep_dumps=0)
        recorder.record("fault_injected", point="x")
        recorder.dump(reason="manual")
        assert os.listdir(tmp_path) == []

    def test_auto_dump_cooldown_coalesces_storms(self, tmp_path):
        """A storm of same-kind trip events within the cooldown writes
        ONE dump (the incident-origin dump survives pruning and the
        reconcile thread pays one fsync pair, not N); a different trip
        kind and a post-cooldown repeat still dump."""
        clock = FakeClock()
        recorder = FlightRecorder(
            dump_dir=str(tmp_path), clock=clock, dump_cooldown_s=30.0
        )
        for _ in range(5):
            recorder.record("circuit_open", group="a/b")
            clock.advance(1.0)
        assert recorder.dumps_written == 1
        recorder.record("fsm_trip", trace_ids=["t1"])
        assert recorder.dumps_written == 2  # per-kind cooldown
        clock.advance(31.0)
        recorder.record("circuit_open", group="a/b")
        assert recorder.dumps_written == 3
        # manual dumps are never throttled
        assert recorder.dump(reason="manual") is not None

    def test_one_incident_one_dump(self, tmp_path):
        """The watchdog-trips-the-FSM pattern: two causally-linked trip
        events for ONE incident write ONE dump (the second, whose ring
        holds both events), via auto_dump=False on the first record;
        when the second trip never fires, maybe_auto_dump still writes
        the first kind's dump under its own cooldown."""
        recorder = FlightRecorder(dump_dir=str(tmp_path))
        recorder.record(
            "watchdog_restart", trace_ids=["t1"], auto_dump=False
        )
        assert recorder.dumps_written == 0
        recorder.record("fsm_trip", trace_ids=["t1"])
        assert recorder.dumps_written == 1
        dumps = sorted(
            name for name in os.listdir(tmp_path)
            if name.startswith("flightrecorder-")
        )
        doc = json.load(open(os.path.join(tmp_path, dumps[0])))
        assert doc["reason"] == "fsm_trip"
        assert [e["kind"] for e in doc["events"]] == [
            "watchdog_restart", "fsm_trip"
        ]
        # the no-trip variant: the deferred dump still happens
        recorder2 = FlightRecorder(dump_dir=str(tmp_path / "x"))
        os.makedirs(tmp_path / "x", exist_ok=True)
        recorder2.record(
            "watchdog_restart", trace_ids=["t2"], auto_dump=False
        )
        assert recorder2.maybe_auto_dump("watchdog_restart") is not None
        assert recorder2.dumps_written == 1

    def test_trip_kinds_auto_dump(self, tmp_path):
        recorder = FlightRecorder(dump_dir=str(tmp_path))
        recorder.record("fsm_trip", trace_ids=["t1"])
        dumps = [
            name for name in os.listdir(tmp_path)
            if name.startswith("flightrecorder-")
        ]
        assert len(dumps) == 1
        doc = json.load(open(os.path.join(tmp_path, dumps[0])))
        assert doc["reason"] == "fsm_trip"
        assert doc["events"][-1]["trace_ids"] == ["t1"]


# -- solver_trace probe caching ----------------------------------------------


class TestSolverTraceProbe:
    def test_unavailable_path_is_shared_noop(self, monkeypatch):
        monkeypatch.setattr(profiler_mod, "_ANNOTATION_CLS", False)
        a = profiler_mod.solver_trace("x")
        b = profiler_mod.solver_trace("y")
        assert a is b is profiler_mod._NOOP_TRACE  # allocation-free

    def test_probe_runs_once(self, monkeypatch):
        monkeypatch.setattr(profiler_mod, "_ANNOTATION_CLS", None)
        calls = {"n": 0}
        real = profiler_mod._probe

        def counting():
            calls["n"] += 1
            return real()

        monkeypatch.setattr(profiler_mod, "_probe", counting)
        with profiler_mod.solver_trace("a"):
            pass
        probed = profiler_mod._ANNOTATION_CLS
        assert probed is not None  # cached (class or False)
        with profiler_mod.solver_trace("b"):
            pass
        assert calls["n"] == 1  # second call hit the cache

    def test_exceptions_propagate(self):
        with pytest.raises(RuntimeError):
            with profiler_mod.solver_trace("x"):
                raise RuntimeError("from the traced block")


# -- exposition conformance (promtool-style lint) ----------------------------


_SERIES_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)


def _lint_exposition(text: str):
    """Minimal promtool check-metrics analog: returns the parsed series
    and raises AssertionError on format violations."""
    typed: dict = {}
    helped: set = set()
    series = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("gauge", "counter", "histogram"), line
            assert name not in typed, f"duplicate TYPE for {name}"
            typed[name] = kind
            continue
        if line.startswith("# HELP "):
            helped.add(line.split(" ", 3)[2])
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        match = _SERIES_RE.match(line)
        assert match, f"unparseable series line: {line!r}"
        name = match.group("name")
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        owner = name if name in typed else base
        assert owner in typed, f"series {name} has no TYPE line"
        if typed[owner] == "histogram" and owner != name:
            assert name.endswith(("_bucket", "_sum", "_count")), line
        value = match.group("value")
        if value not in ("NaN", "+Inf", "-Inf"):
            float(value)
        series.append((name, match.group("labels") or "", value))
    assert set(typed) <= helped, "TYPE without HELP"
    return typed, series


class TestExpositionConformance:
    def _registry(self):
        registry = GaugeRegistry()
        registry.register("queue", "length").set("q", "default", 41.0)
        registry.register("queue", "nan").set("n", "default", float("nan"))
        registry.register(
            "runtime", "reconciles_total", kind="counter"
        ).inc("HA", "-")
        hist = registry.register(
            "solver", "stage_seconds", kind="histogram",
            buckets=(0.001, 0.01, 0.1),
        )
        for value in (0.0005, 0.002, 0.002, 0.05, 7.0):
            hist.observe("dispatch", "-", value)
        return registry, hist

    def test_lint_passes_and_histogram_is_consistent(self):
        registry, hist = self._registry()
        typed, series = _lint_exposition(registry.expose_text())
        assert typed["karpenter_solver_stage_seconds"] == "histogram"
        buckets = [
            (labels, float(value))
            for name, labels, value in series
            if name == "karpenter_solver_stage_seconds_bucket"
        ]
        # le labels parse, cumulative counts are monotone, +Inf present
        les, counts = [], []
        for labels, value in buckets:
            le = re.search(r'le="([^"]+)"', labels).group(1)
            les.append(le)
            counts.append(value)
        assert les[-1] == "+Inf"
        assert counts == sorted(counts), "buckets not cumulative"
        count = next(
            float(v) for n, _l, v in series
            if n == "karpenter_solver_stage_seconds_count"
        )
        total = next(
            float(v) for n, _l, v in series
            if n == "karpenter_solver_stage_seconds_sum"
        )
        assert counts[-1] == count == 5  # +Inf bucket == _count
        assert math.isclose(total, 7.0545, rel_tol=1e-9)
        assert counts[:3] == [1.0, 3.0, 4.0]  # per-ladder cumulation

    def test_label_escaping(self):
        registry = GaugeRegistry()
        registry.register("queue", "length").set(
            'evil"name\\with\nnewline', "default", 1.0
        )
        text = registry.expose_text()
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("karpenter_queue_length{")
        )
        assert '\\"' in line and "\\\\" in line and "\\n" in line
        assert "\n" not in line  # the raw newline never leaks
        _lint_exposition(text)

    def test_histogram_kind_mismatch_rejected(self):
        registry = GaugeRegistry()
        registry.register("solver", "stage_seconds", kind="histogram")
        with pytest.raises(ValueError):
            registry.register("solver", "stage_seconds", kind="gauge")

    def test_histogram_bucket_conflict_rejected(self):
        """A second registration with a DIFFERENT ladder must raise like
        a kind mismatch does — silently landing observations in buckets
        the caller never chose skews histogram_quantile()."""
        registry = GaugeRegistry()
        vec = registry.register(
            "solver", "stage_seconds", kind="histogram",
            buckets=(0.001, 0.01),
        )
        # same ladder (or no ladder) re-registers fine
        assert registry.register(
            "solver", "stage_seconds", kind="histogram",
            buckets=(0.001, 0.01),
        ) is vec
        assert registry.register(
            "solver", "stage_seconds", kind="histogram"
        ) is vec
        with pytest.raises(ValueError):
            registry.register(
                "solver", "stage_seconds", kind="histogram",
                buckets=(0.005, 0.05),
            )


# -- metrics server ----------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


class TestMetricsServer:
    def test_content_types_and_404(self, fresh_tracer, fresh_recorder):
        registry = GaugeRegistry()
        registry.register("queue", "length").set("q", "default", 1.0)
        with fresh_tracer.trace("tick"):
            fresh_recorder.record("fault_injected", point="p")
        server = MetricsServer(registry, port=0, host="127.0.0.1")
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            status, ctype, body = _get(f"{base}/metrics")
            assert status == 200
            assert ctype == "text/plain; version=0.0.4"
            _lint_exposition(body.decode())
            status, ctype, body = _get(f"{base}/debug/traces?limit=10")
            assert status == 200 and ctype == "application/json"
            doc = json.loads(body)
            assert doc["spans"][-1]["name"] == "tick"
            status, ctype, body = _get(f"{base}/debug/flightrecorder")
            assert status == 200
            assert json.loads(body)["events"][0]["point"] == "p"
            assert _get(f"{base}/healthz")[2] == b"ok"
            assert _get(f"{base}/readyz")[2] == b"ok"  # no check wired
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"{base}/nope")
            assert err.value.code == 404
        finally:
            server.stop()

    def test_readyz_reflects_real_state(self):
        state = {"ready": False, "reason": "recovery warm-up: 3 tick(s)"}
        server = MetricsServer(
            GaugeRegistry(), port=0, host="127.0.0.1",
            readiness=lambda: (state["ready"], state["reason"]),
        )
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"{base}/readyz")
            assert err.value.code == 503
            assert b"warm-up" in err.value.read()
            # liveness is NOT readiness: healthz stays ok while not ready
            assert _get(f"{base}/healthz")[2] == b"ok"
            state["ready"] = True
            assert _get(f"{base}/readyz")[0] == 200
        finally:
            server.stop()

    def test_readiness_check_wiring(self):
        """__main__._readiness against the real runtime surface: not
        ready while the solver FSM is degraded, ready once healthy."""
        from karpenter_tpu.__main__ import _readiness
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        runtime = KarpenterRuntime(
            Options(), cloud_provider_factory=FakeFactory()
        )
        try:
            check = _readiness(runtime)
            assert check() == (True, "ok")
            runtime.solver_service._health = "degraded"
            ready, reason = check()
            assert not ready and "degraded" in reason
            runtime.solver_service._health = "healthy"
            assert check()[0]
        finally:
            runtime.close()

    def test_readiness_holds_during_recovery_warmup(self, tmp_path):
        """A RECOVERED boot reports 503 until the warm-up ticks pass —
        the same gate that holds disruption."""
        from karpenter_tpu.__main__ import _readiness
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        opts = Options(
            journal_dir=str(tmp_path), recovery_warmup_ticks=2
        )
        first = KarpenterRuntime(
            opts, cloud_provider_factory=FakeFactory()
        )
        first.recovery.handle("breaker").set(("a", "b"), {"state": "open"})
        first.close()
        runtime = KarpenterRuntime(
            opts, cloud_provider_factory=FakeFactory()
        )
        try:
            assert runtime.recovery.recovered
            check = _readiness(runtime)
            ready, reason = check()
            assert not ready and "warm-up" in reason
            runtime.manager.converge(2)
            assert check() == (True, "ok")
        finally:
            runtime.close()


# -- solver service integration ----------------------------------------------


def _binpack_inputs(n_pods=3, n_groups=2):
    from karpenter_tpu.ops.binpack import BinPackInputs

    return BinPackInputs(
        pod_requests=np.ones((n_pods, 2), np.float32),
        pod_valid=np.ones(n_pods, bool),
        pod_intolerant=np.zeros((n_pods, 4), bool),
        pod_required=np.zeros((n_pods, 4), bool),
        group_allocatable=np.full((n_groups, 2), 8.0, np.float32),
        group_taints=np.zeros((n_groups, 4), bool),
        group_labels=np.ones((n_groups, 4), bool),
    )


class TestSolverTracing:
    def test_coalesced_dispatch_links_batch(self, fresh_tracer):
        from karpenter_tpu.solver import SolverService

        service = SolverService(registry=GaugeRegistry())
        try:
            with fresh_tracer.trace("tick") as root:
                service.consolidate(
                    [_binpack_inputs() for _ in range(3)],
                    backend="numpy",
                )
        finally:
            service.close()
        spans = fresh_tracer.snapshot()
        requests = [s for s in spans if s["name"] == "solver.request"]
        assert len(requests) == 3
        assert all(s["trace"] == root.trace_id for s in requests)
        dispatch = next(
            s for s in spans if s["name"] == "solver.dispatch"
        )
        assert set(dispatch["links"]) == {s["id"] for s in requests}
        assert dispatch["trace"] == root.trace_id

    def test_batch_overflow_records_rejected_spans(self, fresh_tracer):
        """Queue-full rejection in the coalesced consolidate path must
        leave rejected request spans like the singleton path does — a
        saturation trace export has to show the rejected fleet-batch
        candidates, not just rejected singletons."""
        from karpenter_tpu.solver import SolverService

        service = SolverService(registry=GaugeRegistry(), max_queue=0)
        try:
            with fresh_tracer.trace("tick") as root:
                results = service.consolidate(
                    [_binpack_inputs() for _ in range(3)],
                    backend="numpy",
                )
        finally:
            service.close()
        assert len(results) == 3  # overflow degrades to numpy inline
        rejected = [
            s for s in fresh_tracer.snapshot()
            if s["name"] == "solver.request"
            and s["args"].get("rejected") is True
        ]
        assert len(rejected) == 3
        assert all(s["args"]["ok"] is False for s in rejected)
        assert all(s["trace"] == root.trace_id for s in rejected)

    def test_stage_and_coalesce_histograms_fill(self):
        from karpenter_tpu.solver import SolverService

        registry = GaugeRegistry()
        service = SolverService(registry=registry)
        try:
            service.solve(_binpack_inputs(), backend="numpy")
        finally:
            service.close()
        stage = registry.gauge("solver", "stage_seconds")
        assert stage.count("dispatch", "-") >= 1
        coalesce = registry.gauge("solver", "coalesce_batch_size")
        assert coalesce.count("-", "-") >= 1

    def test_abandoned_request_span_closes(self, fresh_tracer):
        """A caller-side timeout sets abandoned without finish(): the
        worker's _filter_live must close the span or the timed-out
        request vanishes from the export."""
        from karpenter_tpu.solver import SolverService
        from karpenter_tpu.solver.service import _Request

        service = SolverService(registry=GaugeRegistry())
        try:
            request = _Request(
                inputs=_binpack_inputs(), buckets=8, backend="numpy",
                key=("solve",), n_pods=3, n_groups=2,
                deadline=None, enqueued_at=0.0,
            )
            service._begin_request_span(request)
            request.abandoned = True
            assert service._filter_live([request]) == []
        finally:
            service.close()
        span = next(
            s for s in fresh_tracer.snapshot()
            if s["name"] == "solver.request"
        )
        assert span["args"]["abandoned"] is True
        assert span["args"]["ok"] is False

    def test_seeded_chaos_trip_dumps_with_backlinks(
        self, fresh_tracer, fresh_recorder, tmp_path
    ):
        """The chaos acceptance pin: injected device failures trip the
        solver FSM, and the flight-recorder dump's fsm_trip event
        backlinks the trace IDs of the degraded requests."""
        from karpenter_tpu.faults import injected_faults
        from karpenter_tpu.solver import SolverService

        fresh_recorder.configure(dump_dir=str(tmp_path))
        service = SolverService(
            registry=GaugeRegistry(), health_failure_threshold=1
        )
        try:
            with injected_faults(seed=7) as faults:
                faults.plan("solver.dispatch", mode="error", times=1)
                with fresh_tracer.trace("tick") as root:
                    out = service.solve(
                        _binpack_inputs(), backend="xla"
                    )
                assert out is not None  # degraded, still answered
        finally:
            service.close()
        assert service.stats.fsm_trips == 1
        trips = fresh_recorder.events(kind="fsm_trip")
        assert len(trips) == 1
        assert root.trace_id in trips[0]["trace_ids"]
        dumps = [
            name for name in os.listdir(tmp_path)
            if name.startswith("flightrecorder-")
            and "fsm_trip" in name
        ]
        assert dumps, "trip did not dump"
        doc = json.load(open(os.path.join(tmp_path, dumps[-1])))
        dumped_trip = next(
            e for e in doc["events"] if e["kind"] == "fsm_trip"
        )
        assert root.trace_id in dumped_trip["trace_ids"]
        injected = fresh_recorder.events(kind="fault_injected")
        assert any(e["point"] == "solver.dispatch" for e in injected)
        # the degraded request's span is DISTINGUISHABLE from a healthy
        # device-served one — the question the backlinks exist to answer
        request_spans = [
            s for s in fresh_tracer.snapshot()
            if s["name"] == "solver.request"
        ]
        assert request_spans
        assert all(
            s["args"].get("degraded") is True for s in request_spans
        )


# -- the end-to-end simulate pin ---------------------------------------------


class TestTraceExportAcceptance:
    def test_simulate_trace_export_end_to_end(
        self, fresh_tracer, fresh_recorder, tmp_path, capsys
    ):
        """ISSUE 9 acceptance: the traced replay emits valid JSONL in
        which a coalesced dispatch links >= 2 request spans whose trace
        roots are tick entries, an actuation span closes the chain, and
        an e2e sample lands."""
        from karpenter_tpu.__main__ import main as cli_main

        path = str(tmp_path / "trace.jsonl")
        rc = cli_main(["--simulate", "--trace-export", path])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["max_dispatch_links"] >= 2
        assert report["actuation_spans"] >= 1
        assert report["e2e_samples"] >= 1
        assert report["replicas_after"] < 3  # the scale-down landed

        events = [
            json.loads(line)
            for line in open(path).read().splitlines()
        ]
        complete = {
            e["id"]: e for e in events if e["ph"] == "X"
        }
        roots_by_trace = {
            e["args"]["trace_id"]: e["name"]
            for e in complete.values()
            if "parent_id" not in e["args"]
        }
        dispatches = [
            e for e in complete.values()
            if e["name"].startswith("solver.dispatch")
            and len(e["args"].get("links", [])) >= 2
        ]
        assert dispatches, "no coalesced dispatch span with >=2 links"
        linked = [
            complete[sid]
            for sid in dispatches[0]["args"]["links"]
        ]
        assert all(s["name"] == "solver.request" for s in linked)
        # every linked request's trace is rooted at a tick entry
        assert all(
            roots_by_trace[s["args"]["trace_id"]] == "reconcile.tick"
            for s in linked
        )
        actuations = [
            e for e in complete.values()
            if e["name"] == "actuate.set_replicas"
        ]
        assert actuations
        assert (
            roots_by_trace[actuations[0]["args"]["trace_id"]]
            == "reconcile.tick"
        )
        # flow events pair up (Perfetto link arrows)
        assert {e["id"] for e in events if e["ph"] == "s"} == {
            e["id"] for e in events if e["ph"] == "f"
        }


# -- overhead regression guard -----------------------------------------------


class TestTracingOverheadGuard:
    def _tick_p50(self, enabled: bool, ticks: int = 30) -> float:
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options
        from karpenter_tpu.simulate import simulate_trace  # noqa: F401

        tracer = default_tracer()
        tracer.enabled = enabled
        runtime = KarpenterRuntime(
            Options(), cloud_provider_factory=FakeFactory()
        )
        try:
            from karpenter_tpu.api.core import ObjectMeta
            from karpenter_tpu.api.metricsproducer import (
                MetricsProducer, MetricsProducerSpec,
                PendingCapacitySpec,
            )

            runtime.store.create(MetricsProducer(
                metadata=ObjectMeta(name="pending"),
                spec=MetricsProducerSpec(
                    pending_capacity=PendingCapacitySpec(
                        node_selector={"pool": "a"},
                    )
                ),
            ))
            times = []
            for _ in range(5):
                runtime.manager.converge(1)  # warm caches
            for _ in range(ticks):
                t0 = time.perf_counter()
                runtime.manager.converge(1)
                times.append(time.perf_counter() - t0)
        finally:
            runtime.close()
            tracer.enabled = True
        return float(np.percentile(times, 50))

    def test_span_volume_per_tick_is_bounded(self, fresh_tracer):
        """The structural guard: tracing cost is O(spans), so pin the
        span count a tick may mint — a regression to per-object or
        per-row span work shows up here long before wall clock."""
        before = fresh_tracer.spans_total
        self._tick_p50(enabled=True, ticks=10)
        per_tick = (fresh_tracer.spans_total - before) / 15.0
        assert per_tick <= 20, f"{per_tick:.1f} spans/tick"

    def test_enabled_vs_disabled_tick_overhead(self, fresh_tracer):
        """The wall-clock guard, with generous flake headroom: `make
        bench-trace` publishes the honest <5% number (docs/BENCHMARKS.md);
        this pin catches gross regressions (>75% on sub-ms ticks)."""
        off = self._tick_p50(enabled=False)
        on = self._tick_p50(enabled=True)
        assert on <= off * 1.75 + 0.002, (
            f"tracing overhead p50 {off * 1e3:.3f}ms -> "
            f"{on * 1e3:.3f}ms"
        )


class TestTenantDebugFilters:
    """Satellite (ISSUE 12): per-tenant filtering on the existing debug
    surfaces — /debug/traces?tenant= keeps whole traces that touched
    the tenant (tenant-stamped solver-request / tenancy-serve spans),
    /debug/flightrecorder?tenant= keeps that tenant's events."""

    def test_traces_tenant_filter_keeps_whole_traces(
        self, fresh_tracer, fresh_recorder
    ):
        with fresh_tracer.trace("tick-a"):
            with fresh_tracer.span("solver.request", tenant="t1"):
                pass
            with fresh_tracer.span("actuate"):
                pass
        with fresh_tracer.trace("tick-b"):
            with fresh_tracer.span("solver.request", tenant="t2"):
                pass
        registry = GaugeRegistry()
        server = MetricsServer(registry, port=0, host="127.0.0.1")
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            _status, _ctype, body = _get(f"{base}/debug/traces?tenant=t1")
            spans = json.loads(body)["spans"]
            names = sorted(s["name"] for s in spans)
            # the WHOLE trace that touched t1 — including its untagged
            # actuation span and root — but nothing of tick-b
            assert names == ["actuate", "solver.request", "tick-a"]
            _status, _c, body = _get(f"{base}/debug/traces?tenant=nope")
            assert json.loads(body)["spans"] == []
            # limit applies AFTER the filter
            _status, _c, body = _get(
                f"{base}/debug/traces?tenant=t1&limit=1"
            )
            assert len(json.loads(body)["spans"]) == 1
        finally:
            server.stop()

    def test_flightrecorder_tenant_filter(self, fresh_recorder):
        fresh_recorder.record(
            "tenant_breaker_trip", tenant="t1", error="boom"
        )
        fresh_recorder.record(
            "tenant_breaker_trip", tenant="t2", error="boom"
        )
        fresh_recorder.record("fsm_trip", subsystem="solver")
        server = MetricsServer(GaugeRegistry(), port=0, host="127.0.0.1")
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            _s, _c, body = _get(
                f"{base}/debug/flightrecorder?tenant=t1"
            )
            events = json.loads(body)["events"]
            assert len(events) == 1
            assert events[0]["tenant"] == "t1"
            _s, _c, body = _get(
                f"{base}/debug/flightrecorder"
                f"?kind=tenant_breaker_trip&tenant=t2"
            )
            events = json.loads(body)["events"]
            assert [e["tenant"] for e in events] == ["t2"]
        finally:
            server.stop()

    def test_scheduler_breaker_trip_records_tenant_event(
        self, fresh_recorder
    ):
        """The tenancy board's breaker trips land in the flight
        recorder WITH the tenant field the filter keys on."""
        from karpenter_tpu.metrics.registry import (
            GaugeRegistry as Registry,
        )
        from karpenter_tpu.solver import SolverService
        from karpenter_tpu.tenancy import (
            MultiTenantScheduler,
            TenantRegistry,
            TenantSpec,
        )

        service = SolverService(registry=Registry())
        registry = TenantRegistry(
            service=service, registry=Registry(),
            specs=[TenantSpec(id="bad"), TenantSpec(id="good")],
        )
        scheduler = MultiTenantScheduler(
            registry, service, breaker_threshold=1
        )
        try:
            from karpenter_tpu import faults
            from karpenter_tpu.faults import FaultRegistry
            from karpenter_tpu.simulate import (
                multitenant_fleet_inputs,
            )

            fault_registry = faults.install(FaultRegistry(seed=7))
            fault_registry.plan(
                "tenancy.gather.bad", probability=1.0
            )
            batch = {
                tenant: multitenant_fleet_inputs(
                    i, 2, 1, 0, 0,
                    __import__("numpy").full(2, 2, "int32"), 1e6,
                )
                for i, tenant in enumerate(("bad", "good"))
            }
            scheduler.decide_all(batch)
            trips = [
                e for e in fresh_recorder.events()
                if e["kind"] == "tenant_breaker_trip"
            ]
            assert trips and trips[0]["tenant"] == "bad"
        finally:
            faults.uninstall()
            service.close()
