"""Seeded chaos suite: the runtime and simulator under injected faults.

Every scenario runs under a FIXED fault-registry seed (plans own their
RNG streams — faults/registry.py), so these are deterministic replays,
not flaky roulette. The invariants asserted are the ISSUE's acceptance
bar for the degradation ladder:

  * no lost solver requests — every solve completes (device, numpy
    fallback, or watchdog drain), the queue ends empty;
  * the solver backend FSM trips to numpy under repeated device faults
    and recovers via probes once the device heals;
  * the actuation circuit breaker opens on a flapping provider (with
    the structured ActuationCircuitOpen condition + error code) and
    closes through a half-open probe;
  * no duplicate scale actuations — each successful (group, count)
    provider write happens at most once;
  * fleet replicas converge to the no-fault fixed point within 10 ticks
    of faults clearing.

`make test-chaos` runs exactly this file + tests/test_faults.py.
"""

from __future__ import annotations

import numpy as np
import pytest

from karpenter_tpu import faults
from karpenter_tpu.api import conditions as cond
from karpenter_tpu.api.core import (
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    resource_list,
)
from karpenter_tpu.api.horizontalautoscaler import (
    CrossVersionObjectReference,
    HorizontalAutoscaler,
    HorizontalAutoscalerSpec,
    Metric,
    MetricTarget,
    PrometheusMetricSource,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    MetricsProducerSpec,
    PendingCapacitySpec,
)
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.cloudprovider.fake import FakeFactory, FakeNodeGroup, retryable_error
from karpenter_tpu.faults import FaultRegistry
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.runtime import KarpenterRuntime, Options
from karpenter_tpu.solver import SolverService

from test_binpack import make_inputs

CHAOS_SEED = 20260803


@pytest.fixture(autouse=True)
def _no_leaked_registry():
    yield
    faults.uninstall()


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


class RecordingNodeGroup(FakeNodeGroup):
    def set_replicas(self, count):
        super().set_replicas(count)
        self._factory.actuations.append((self._id, count))


class RecordingFactory(FakeFactory):
    """FakeFactory that records every SUCCESSFUL actuation — retries of
    a failed write are legitimate; a repeated successful write of the
    same transition is a duplicate actuation."""

    def __init__(self):
        super().__init__()
        self.actuations = []

    def node_group_for(self, spec):
        return RecordingNodeGroup(self, spec.id)


def sng_of(name, replicas):
    return ScalableNodeGroup(
        metadata=ObjectMeta(name=name),
        spec=ScalableNodeGroupSpec(
            replicas=replicas, type="FakeNodeGroup", id=name
        ),
    )


def queue_ha(name, target_query, min_replicas=3, max_replicas=100):
    return HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name=name
            ),
            min_replicas=min_replicas,
            max_replicas=max_replicas,
            metrics=[
                Metric(
                    prometheus=PrometheusMetricSource(
                        query=target_query,
                        target=MetricTarget(type="AverageValue", value=4),
                    )
                )
            ],
        ),
    )


def pending_capacity_world(store):
    """One profiled node group + one pending pod: every producer tick
    drives exactly one solve through the shared service."""
    store.create(
        Node(
            metadata=ObjectMeta(name="n1", labels={"pool": "a"}),
            spec=NodeSpec(),
            status=NodeStatus(
                allocatable=resource_list(cpu="8", memory="16Gi", pods="16"),
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
    )
    store.create(
        Pod(metadata=ObjectMeta(name="p1"), spec=PodSpec())  # pending
    )
    mp = MetricsProducer(
        metadata=ObjectMeta(name="pending"),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(node_selector={"pool": "a"})
        ),
    )
    store.create(mp)
    return mp


class TestChaosScenario:
    """The acceptance scenario: 50 ticks with solver device faults at
    30%, a flapping provider, flaky metric reads and status writes —
    then faults clear and the fleet must converge within 10 ticks."""

    FIXED_POINT = 11  # queue=41, AverageValue target=4 -> ceil(41/4)

    def make_runtime(self):
        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["g"] = 5
        runtime = KarpenterRuntime(
            Options(
                # ladder knobs tightened so 50 short ticks exercise
                # every rung (docs/resilience.md documents the defaults)
                solver_health_threshold=2,
                solver_probe_interval_s=0.0,  # probe every dispatch
                circuit_failure_threshold=3,
                circuit_reset_s=100.0,
                backoff_base_s=1.0,
                backoff_cap_s=60.0,
            ),
            cloud_provider_factory=provider,
            clock=clock,
        )
        # the virtual-CPU test backend resolves "auto" to numpy; pin the
        # XLA device path so solver faults hit a real device dispatch
        runtime.solver_service.backend = "xla"
        return runtime, provider, clock

    def tick(self, runtime, clock, n=1):
        """One manager tick with CLUSTER CHURN: a pod toggles existence
        each tick, so the producer's encode-memo (which rightly
        short-circuits solves for an unchanged cluster) misses and every
        tick drives a real solve through the service."""
        for _ in range(n):
            self._toggle_churn_pod(runtime)
            clock.advance(61.0)  # everything (SNG interval 60) is due
            runtime.manager.reconcile_all()

    def _toggle_churn_pod(self, runtime):
        try:
            runtime.store.delete("Pod", "default", "churn-pod")
        except KeyError:
            runtime.store.create(
                Pod(metadata=ObjectMeta(name="churn-pod"), spec=PodSpec())
            )

    def _remove_churn_pod(self, runtime):
        try:
            runtime.store.delete("Pod", "default", "churn-pod")
        except KeyError:
            pass

    def test_converges_after_faults_clear(self):
        runtime, provider, clock = self.make_runtime()
        mp = pending_capacity_world(runtime.store)
        runtime.registry.register("queue", "length").set(
            "q", "default", 41.0
        )
        runtime.store.create(sng_of("g", replicas=5))
        runtime.store.create(
            queue_ha("g", 'karpenter_queue_length{name="q"}')
        )
        service = runtime.solver_service
        try:
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan(
                "solver.dispatch", probability=0.3, code="DeviceFault"
            )
            registry.plan(
                "cloud.set_replicas", probability=0.9, code="Throttling"
            )
            registry.plan(
                "cloud.get_replicas", probability=0.3, code="Throttling"
            )
            registry.plan("metrics.query", probability=0.2)
            registry.plan("store.patch_status", probability=0.05)
            self.tick(runtime, clock, n=50)

            assert registry.injected.get("solver.dispatch", 0) >= 5, (
                "the scenario must actually have exercised device faults"
            )
            # the FSM tripped under the 30% device-fault stream and
            # recovered through a probe while faults were still active
            assert service.stats.fsm_trips >= 1
            assert service.stats.fsm_recoveries >= 1
            # no lost requests: everything submitted was answered
            # (device or numpy) and nothing is stuck in the queue
            assert service.queue_depth() == 0
            assert service.stats.requests >= 50
            assert service.stats.fallbacks >= 1
            # the circuit opened at least once against the 90%-flaky
            # actuation path
            opens = runtime.registry.gauge(
                "resilience", "circuit_open_total"
            ).get("g", "default")
            assert opens is not None and opens >= 1

            faults.uninstall()  # ---- faults clear ----

            converged_at = None
            for i in range(10):
                self.tick(runtime, clock)
                if provider.node_replicas["g"] == self.FIXED_POINT:
                    converged_at = i
                    break
            assert converged_at is not None, (
                f"fleet must converge to {self.FIXED_POINT} within 10 "
                f"ticks of faults clearing; stuck at "
                f"{provider.node_replicas['g']}"
            )
            self._remove_churn_pod(runtime)
            self.tick(runtime, clock, n=2)  # settle status/conditions
            self._remove_churn_pod(runtime)
            clock.advance(61.0)
            runtime.manager.reconcile_all()  # final churn-free solve

            assert service.backend_health() == "healthy"
            ha = runtime.store.get("HorizontalAutoscaler", "default", "ha")
            assert ha.status.desired_replicas == self.FIXED_POINT
            sng = runtime.store.get("ScalableNodeGroup", "default", "g")
            assert sng.status.replicas == self.FIXED_POINT
            assert (
                sng.status_conditions().get(cond.ABLE_TO_SCALE).status
                == cond.TRUE
            )
            # the pending-capacity producer kept producing through the
            # whole outage (numpy fallback): status populated and happy
            mp = runtime.store.get(
                "MetricsProducer", "default", "pending"
            )
            assert mp.status.pending_capacity is not None
            assert mp.status.pending_capacity.pending_pods == 1
            assert (
                mp.status_conditions().get(cond.ACTIVE).status == cond.TRUE
            )
            # no duplicate actuations: every successful (group, count)
            # write is unique — retries of FAILED writes don't repeat a
            # landed transition
            assert len(provider.actuations) == len(
                set(provider.actuations)
            ), f"duplicate actuation in {provider.actuations}"
        finally:
            runtime.close()

    def test_scenario_is_deterministic(self):
        """Same seed, same world → identical actuation history and
        fault counts: the suite is a replay, not a dice roll."""

        def run():
            runtime, provider, clock = self.make_runtime()
            pending_capacity_world(runtime.store)
            runtime.registry.register("queue", "length").set(
                "q", "default", 41.0
            )
            runtime.store.create(sng_of("g", replicas=5))
            runtime.store.create(
                queue_ha("g", 'karpenter_queue_length{name="q"}')
            )
            try:
                with FaultRegistry(seed=CHAOS_SEED) as registry:
                    registry.plan("cloud.set_replicas", probability=0.9)
                    registry.plan("cloud.get_replicas", probability=0.3)
                    registry.plan("metrics.query", probability=0.2)
                    self.tick(runtime, clock, n=25)
                    return (
                        list(provider.actuations),
                        dict(registry.injected),
                        provider.node_replicas["g"],
                    )
            finally:
                runtime.close()

        assert run() == run()


class TestSimulateUnderFaults:
    def test_simulate_report_identical_with_device_faults(self):
        """The dry-run simulator under 100% device faults: every solve
        degrades to numpy and the REPORT IS IDENTICAL — the fallback
        path is not a lesser answer (device/numpy parity is pinned by
        the solver oracle suites)."""
        from karpenter_tpu.simulate import simulate
        from karpenter_tpu.store import Store

        store = Store()
        pending_capacity_world(store)
        service = SolverService(
            registry=GaugeRegistry(), backend="xla",
            health_failure_threshold=3,
        )
        try:
            baseline = simulate(store, solver=service.solve)
            with FaultRegistry(seed=CHAOS_SEED) as registry:
                registry.plan("solver.dispatch", probability=1.0)
                for _ in range(4):  # enough to trip the FSM mid-run
                    faulty = simulate(store, solver=service.solve)
                    assert faulty == baseline
            assert service.stats.fallbacks >= 1
            assert service.stats.fsm_trips == 1
        finally:
            service.close()


class TestForecastChaos:
    """Satellite pin: forecast-path device faults degrade DOWN the
    ladder (numpy mirror first, reactive-only at worst) and NEVER block
    the reconcile loop — the fleet converges to the same fixed point a
    fault-free reactive run reaches."""

    FIXED_POINT = 11  # queue=41, AverageValue target=4 -> ceil(41/4)

    def test_forecast_device_faults_degrade_not_block(self):
        from karpenter_tpu.api.horizontalautoscaler import ForecastSpec

        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["g"] = 5
        runtime = KarpenterRuntime(
            Options(solver_health_threshold=2,
                    solver_probe_interval_s=0.0),
            cloud_provider_factory=provider,
            clock=clock,
        )
        runtime.solver_service.backend = "xla"
        runtime.registry.register("queue", "length").set(
            "q", "default", 41.0
        )
        runtime.store.create(sng_of("g", replicas=5))
        ha = queue_ha("g", 'karpenter_queue_length{name="q"}')
        ha.spec.behavior.forecast = ForecastSpec(
            horizon_seconds=30.0, model="linear", min_samples=3
        )
        runtime.store.create(ha)
        service = runtime.solver_service
        try:
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan("forecast.predict", probability=1.0)
            for _ in range(30):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            assert registry.injected.get("forecast.predict", 0) >= 1, (
                "the scenario must actually have exercised forecast "
                "faults"
            )
            # every forecast answered from the numpy mirror; the
            # reconcile loop never stalled and the fleet sits at the
            # reactive fixed point (a flat metric forecasts flat)
            assert service.stats.forecast_calls >= 20
            assert service.stats.fallbacks >= 1
            assert service.queue_depth() == 0
            assert provider.node_replicas["g"] == self.FIXED_POINT
            got = runtime.store.get(
                "HorizontalAutoscaler", "default", "ha"
            )
            assert got.status.desired_replicas == self.FIXED_POINT
            # the repeated device faults tripped the backend FSM — the
            # forecast path feeds the SAME health ladder bin-packs do
            assert service.stats.fsm_trips >= 1

            faults.uninstall()  # ---- faults clear ----
            for _ in range(3):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            assert service.backend_health() == "healthy"
            assert provider.node_replicas["g"] == self.FIXED_POINT
        finally:
            faults.uninstall()
            runtime.close()


class TestFusedTickChaos:
    """Satellite pin (docs/solver-service.md "Fused tick"): 100%
    fused-program faults walk the never-block ladder — chained
    per-stage fallback first, the numpy floor once the FSM trips —
    and the fleet still reaches the fixed point a never-fused run
    reaches. reset_caches() re-arms the fused compile key."""

    FIXED_POINT = 11  # queue=41, AverageValue target=4 -> ceil(41/4)

    def _world(self, fused: bool):
        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["g"] = 5
        runtime = KarpenterRuntime(
            Options(fused_tick=fused,
                    solver_health_threshold=2,
                    solver_probe_interval_s=0.0),
            cloud_provider_factory=provider,
            clock=clock,
        )
        runtime.solver_service.backend = "xla"
        runtime.registry.register("queue", "length").set(
            "q", "default", 41.0
        )
        runtime.store.create(sng_of("g", replicas=5))
        runtime.store.create(
            queue_ha("g", 'karpenter_queue_length{name="q"}')
        )
        return runtime, provider, clock

    def test_fused_faults_degrade_down_the_ladder(self):
        # the never-fused reference: same world, no faults
        baseline, base_provider, base_clock = self._world(fused=False)
        try:
            for _ in range(10):
                base_clock.advance(61.0)
                baseline.manager.reconcile_all()
            assert base_provider.node_replicas["g"] == self.FIXED_POINT
        finally:
            baseline.close()

        runtime, provider, clock = self._world(fused=True)
        service = runtime.solver_service
        try:
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan("fused.tick", probability=1.0)
            for _ in range(10):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            assert registry.injected.get("fused.tick", 0) >= 1, (
                "the scenario must actually have exercised fused faults"
            )
            # every faulted tick served from the CHAINED rung (probe
            # interval 0 keeps the device attempt live), bit-identical
            # to the never-fused wire: same fixed point
            assert service.stats.fused_chained_serves >= 1
            assert service.stats.fused_dispatches == 0
            assert service.queue_depth() == 0
            assert provider.node_replicas["g"] == self.FIXED_POINT
            # the fused path feeds the SAME backend-health FSM
            assert service.stats.fsm_trips >= 1

            # park the probes: a DEGRADED plane short-circuits to the
            # numpy floor without touching the device
            service.health_probe_interval_s = 3600.0
            mirrors_before = service.stats.fused_mirror_serves
            for _ in range(4):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            assert service.stats.fused_mirror_serves > mirrors_before
            assert provider.node_replicas["g"] == self.FIXED_POINT

            faults.uninstall()  # ---- faults clear ----
            service.health_probe_interval_s = 0.0
            service._next_probe = 0.0
            for _ in range(3):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            assert service.backend_health() == "healthy"
            assert service.stats.fused_dispatches >= 1
            assert provider.node_replicas["g"] == self.FIXED_POINT

            # reset_caches re-arms the fused compile key: the next
            # dispatch counts a fresh compile again
            misses = service.stats.compile_cache_misses
            service.reset_caches()
            clock.advance(61.0)
            runtime.manager.reconcile_all()
            assert service.stats.compile_cache_misses == misses + 1
            assert provider.node_replicas["g"] == self.FIXED_POINT
        finally:
            faults.uninstall()
            runtime.close()


class TestPreemptChaos:
    """Satellite pin (docs/preemption.md): eviction planning under
    device faults degrades to the BIT-IDENTICAL numpy mirror — plans
    keep landing, budgets hold, no victim is ever evicted twice — and
    the repeated faults trip the shared backend-health FSM."""

    BUDGET = 2

    def _storm(self):
        from test_preemption import make_pod, storm_store

        store = storm_store(eviction_budget=self.BUDGET)
        for i in range(3):
            store.create(
                make_pod(f"crit-{i}", cpu="2", priority=1000 - i)
            )
        return store

    def test_device_faults_degrade_to_mirror_with_budgets_held(self):
        from karpenter_tpu.preemption import (
            PreemptionConfig,
            PreemptionEngine,
        )

        store = self._storm()
        clock = FakeClock()
        service = SolverService(
            registry=GaugeRegistry(), backend="xla",
            health_failure_threshold=2,
            health_probe_interval_s=0.0,
        )
        engine = PreemptionEngine(
            store, service,
            config=PreemptionConfig(
                min_candidate_priority=1, plan_interval_s=0.0,
                budget_per_group=self.BUDGET, hold_s=30.0,
            ),
            clock=clock,
        )
        evicted_ever = []
        try:
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan("preempt.plan", probability=1.0)
            per_round = []
            for _ in range(6):
                plans = engine.plan(clock.now)
                round_evicted = [
                    key
                    for p in plans.values()
                    if p
                    for key in p["evictions"]
                ]
                per_round.append(len(round_evicted))
                evicted_ever.extend(round_evicted)
                clock.advance(61.0)  # holds + budget charges expire
            assert registry.injected.get("preempt.plan", 0) >= 1, (
                "the scenario must actually have exercised preempt "
                "faults"
            )
            # every plan answered from the bit-identical numpy mirror:
            # evictions still landed, the loop never stalled
            assert service.stats.fallbacks >= 1
            assert sum(per_round) >= 2
            assert service.queue_depth() == 0
            # budgets NEVER exceeded, even while degraded
            assert all(n <= self.BUDGET for n in per_round), per_round
            # no duplicate evictions across the whole storm
            assert len(evicted_ever) == len(set(evicted_ever))
            # the repeated device faults fed the shared FSM
            assert service.stats.fsm_trips >= 1

            faults.uninstall()  # ---- faults clear ----
            clock.advance(61.0)
            engine.plan(clock.now)
            assert service.backend_health() == "healthy"
        finally:
            faults.uninstall()
            service.close()


class TestSolverFSM:
    def test_trips_wholesale_and_recovers_via_probe(self):
        service = SolverService(
            registry=GaugeRegistry(), backend="xla",
            health_failure_threshold=2,
            health_probe_interval_s=3600.0,  # no implicit probes
        )
        inputs = make_inputs(
            pod_requests=[[1, 1], [3, 1]], group_allocatable=[[4, 4]]
        )
        expect = None
        try:
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan("solver.dispatch", mode="flaky", times=2)
            for _ in range(2):
                out = service.solve(inputs, buckets=8)
            assert service.backend_health() == "degraded"
            assert service.stats.fsm_trips == 1
            attempts_at_trip = registry.attempts["solver.dispatch"]
            # degraded: requests are served WHOLESALE from numpy — the
            # device (and so the injection point) is never attempted
            for _ in range(3):
                out = service.solve(inputs, buckets=8)
            assert registry.attempts["solver.dispatch"] == attempts_at_trip
            assert service.stats.fsm_short_circuits >= 3
            # force the probe window open: the next dispatch rides the
            # device (plan exhausted -> succeeds) and recovers the FSM
            with service._health_lock:
                service._next_probe = 0.0
            out = service.solve(inputs, buckets=8)
            assert service.backend_health() == "healthy"
            assert service.stats.fsm_probes >= 1
            assert service.stats.fsm_recoveries == 1
            from karpenter_tpu.ops.numpy_binpack import binpack_numpy

            expect = binpack_numpy(inputs, buckets=8)
            np.testing.assert_array_equal(
                np.asarray(out.assigned), np.asarray(expect.assigned)
            )
        finally:
            faults.uninstall()
            service.close()


class TestWatchdog:
    def test_restarts_hung_worker_and_drains_to_numpy(self):
        """A hang plan wedges the worker inside a device section; the
        watchdog must supersede it, answer the stuck request from numpy,
        and leave the service serving on a fresh worker."""
        from karpenter_tpu.ops.numpy_binpack import binpack_numpy

        service = SolverService(
            registry=GaugeRegistry(), backend="xla",
            watchdog_timeout_s=0.2,
            health_failure_threshold=10,  # one hang must not trip FSM
        )
        inputs = make_inputs(
            pod_requests=[[1, 1], [3, 1]], group_allocatable=[[4, 4]]
        )
        expect = binpack_numpy(inputs, buckets=8)
        try:
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan("solver.dispatch", mode="hang", times=1)
            out = service.solve(inputs, buckets=8, timeout=30.0)
            np.testing.assert_array_equal(
                np.asarray(out.assigned), np.asarray(expect.assigned)
            )
            assert service.stats.watchdog_restarts == 1
            assert service.backend_health() == "healthy"
            # release the superseded worker's hang; its late unwind must
            # not disturb the fresh worker
            faults.uninstall()
            out2 = service.solve(inputs, buckets=8, timeout=30.0)
            np.testing.assert_array_equal(
                np.asarray(out2.assigned), np.asarray(expect.assigned)
            )
            assert service.stats.watchdog_restarts == 1
        finally:
            faults.uninstall()
            service.close()


class TestActuationCircuit:
    def test_opens_with_structured_condition_then_probe_heals(self):
        clock = FakeClock()
        provider = FakeFactory()
        provider.node_replicas["g"] = 1
        runtime = KarpenterRuntime(
            Options(circuit_failure_threshold=3, circuit_reset_s=100.0),
            cloud_provider_factory=provider,
            clock=clock,
        )
        try:
            runtime.store.create(sng_of("g", replicas=2))
            provider.want_err = retryable_error("Throttling")
            for _ in range(3):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            sng = runtime.store.get("ScalableNodeGroup", "default", "g")
            able = sng.status_conditions().get(cond.ABLE_TO_SCALE)
            assert able.status == cond.FALSE
            assert able.reason == cond.ACTUATION_CIRCUIT_OPEN
            assert "Throttling" in able.message, (
                "the RetryableError.code must thread into the message"
            )
            assert "next probe" in able.message
            # resource stays ACTIVE: an open circuit is supervised
            # degradation, not a resource fault
            assert (
                sng.status_conditions().get(cond.ACTIVE).status
                == cond.TRUE
            )
            # while open, the provider is NOT called (attempts counted
            # by an empty fault registry — observation only)
            with FaultRegistry(seed=0) as registry:
                clock.advance(61.0)
                runtime.manager.reconcile_all()
                assert registry.attempts.get("cloud.get_replicas", 0) == 0
                assert registry.attempts.get("cloud.set_replicas", 0) == 0
            state = runtime.registry.gauge(
                "resilience", "circuit_state"
            ).get("g", "default")
            assert state == 1.0  # open
            # provider heals; once the reset window passes, the single
            # half-open probe reconcile closes the circuit AND actuates
            provider.want_err = None
            clock.advance(61.0)  # cumulative > reset_s since opening
            runtime.manager.reconcile_all()
            assert provider.node_replicas["g"] == 2
            sng = runtime.store.get("ScalableNodeGroup", "default", "g")
            able = sng.status_conditions().get(cond.ABLE_TO_SCALE)
            assert able.status == cond.TRUE
            assert runtime.registry.gauge(
                "resilience", "circuit_state"
            ).get("g", "default") == 0.0
        finally:
            runtime.close()


class TestCostChaos:
    """Satellite pin (docs/cost.md degradation contract): cost-kernel
    faults make the tick COST-BLIND — the base reactive decision stands,
    the reconcile loop never blocks — and the repeated device failures
    feed the SAME backend-health FSM everything else rides; once faults
    clear, probes recover the device path and the multi-objective
    refinement resumes."""

    REACTIVE = 11  # queue 41 / AverageValue target 4 -> ceil
    COST_AWARE = 14  # 41 demand / 3-per-replica sloTarget -> ceil

    def test_cost_faults_degrade_to_cost_blind_then_recover(self):
        from karpenter_tpu.api.horizontalautoscaler import SLOSpec

        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["g"] = 5
        runtime = KarpenterRuntime(
            Options(solver_health_threshold=2,
                    solver_probe_interval_s=0.0),
            cloud_provider_factory=provider,
            clock=clock,
        )
        runtime.solver_service.backend = "xla"
        runtime.registry.register("queue", "length").set(
            "q", "default", 41.0
        )
        runtime.store.create(sng_of("g", replicas=5))
        ha = queue_ha("g", 'karpenter_queue_length{name="q"}')
        # an sloTarget below the HPA target prices risk into extra
        # replicas, so the cost-aware and cost-blind fixed points are
        # DISTINGUISHABLE (14 vs 11) and the degradation is observable
        ha.spec.behavior.slo = SLOSpec(
            target_value=3.0, violation_cost_weight=100.0
        )
        runtime.store.create(ha)
        service = runtime.solver_service
        try:
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan("cost.score", probability=1.0)
            for _ in range(30):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            assert registry.injected.get("cost.score", 0) >= 1, (
                "the scenario must actually have exercised cost faults"
            )
            # every tick went COST-BLIND (the unrefined reactive
            # decision, NOT a mirror-served refinement) and the loop
            # never stalled
            assert service.stats.cost_errors >= 1
            assert service.queue_depth() == 0
            assert provider.node_replicas["g"] == self.REACTIVE
            got = runtime.store.get(
                "HorizontalAutoscaler", "default", "ha"
            )
            assert got.status.desired_replicas == self.REACTIVE
            assert runtime.registry.gauge("cost", "blind_total").get(
                "ha", "default"
            ) >= 1.0
            # the repeated device faults tripped the shared FSM — the
            # cost path feeds the SAME health ladder bin-packs do
            assert service.stats.fsm_trips >= 1

            faults.uninstall()  # ---- faults clear ----
            for _ in range(5):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            # probes re-arm the device path; the refinement resumes and
            # the fleet moves to the cost-aware fixed point
            assert service.backend_health() == "healthy"
            assert service.stats.cost_dispatches >= 1
            assert provider.node_replicas["g"] == self.COST_AWARE
        finally:
            faults.uninstall()
            runtime.close()


class TestPoolGroupChaos:
    """PR 20 satellite (docs/poolgroups.md degradation contract): at
    100% `poolgroup.solve` faults the joint allocator degrades to
    INDEPENDENT per-pool cost ladders — each pool still refines, the
    declared ratio band goes advisory, the reconcile loop never blocks
    — and the repeated device failures feed the SAME backend-health FSM
    every other family rides; once faults clear, probes recover the
    device path and the fleet converges to the JOINT fixed point."""

    PREFILL = 11  # queue 41 / AverageValue target 4 -> ceil
    DECODE_INDEPENDENT = 40  # queue 160 / 4: the per-pool ladder's point
    DECODE_JOINT = 44  # min band decode:prefill >= 4:1 -> 4 * 11

    def _world(self):
        from karpenter_tpu.api.poolgroup import (
            PoolGroup,
            PoolGroupSpec,
            PoolMember,
            RatioConstraint,
        )

        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["g-prefill"] = 5
        provider.node_replicas["g-decode"] = 5
        runtime = KarpenterRuntime(
            Options(poolgroups=True, solver_health_threshold=2,
                    solver_probe_interval_s=0.0),
            cloud_provider_factory=provider,
            clock=clock,
        )
        runtime.solver_service.backend = "xla"
        queue = runtime.registry.register("queue", "length")
        queue.set("qp", "default", 41.0)
        queue.set("qd", "default", 160.0)
        for name, q in (("prefill", "qp"), ("decode", "qd")):
            runtime.store.create(sng_of(f"g-{name}", replicas=5))
            ha = queue_ha(
                f"g-{name}",
                f'karpenter_queue_length{{name="{q}"}}',
                min_replicas=1, max_replicas=1000,
            )
            ha.metadata.name = name
            runtime.store.create(ha)
        # a min-band out of reach of the independent points (decode 40
        # < 4 x prefill 11), so the joint and degraded-independent
        # fixed points are DISTINGUISHABLE (44 vs 40) and the
        # degradation is observable on the wire
        runtime.store.create(PoolGroup(
            metadata=ObjectMeta(name="serving"),
            spec=PoolGroupSpec(
                pools=[PoolMember(name="prefill"),
                       PoolMember(name="decode")],
                ratios=[RatioConstraint(
                    numerator="decode", denominator="prefill",
                    min_numerator=4, min_denominator=1,
                )],
            ),
        ))
        return clock, provider, runtime

    def test_joint_faults_degrade_to_independent_then_recover(self):
        clock, provider, runtime = self._world()
        service = runtime.solver_service
        try:
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan("poolgroup.solve", probability=1.0)
            for _ in range(30):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            assert registry.injected.get("poolgroup.solve", 0) >= 1, (
                "the scenario must actually have exercised joint faults"
            )
            # every tick served the INDEPENDENT per-pool ladders (each
            # pool at its own reactive point, the band advisory) and
            # the loop never stalled
            assert service.stats.poolgroup_independent_serves >= 1
            assert service.queue_depth() == 0
            assert provider.node_replicas["g-prefill"] == self.PREFILL
            assert (
                provider.node_replicas["g-decode"]
                == self.DECODE_INDEPENDENT
            )
            # the degradation is visible on the group: uncoordinated
            # status, ratio_ok gauge down, coordinated counter flat
            group = runtime.store.get("PoolGroup", "default", "serving")
            assert group.status.coordinated is False
            assert runtime.registry.gauge("poolgroup", "ratio_ok").get(
                "serving", "default"
            ) == 0.0
            assert not runtime.registry.gauge(
                "poolgroup", "coordinated_total"
            ).get("serving", "default")
            # the repeated device faults tripped the shared FSM — the
            # joint path feeds the SAME health ladder bin-packs do
            assert service.stats.fsm_trips >= 1

            faults.uninstall()  # ---- faults clear ----
            for _ in range(5):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            # probes re-arm the device path; the joint dispatch resumes
            # and the fleet converges to the coordinated fixed point
            assert service.backend_health() == "healthy"
            assert service.stats.poolgroup_dispatches >= 1
            assert provider.node_replicas["g-prefill"] == self.PREFILL
            assert (
                provider.node_replicas["g-decode"] == self.DECODE_JOINT
            )
            group = runtime.store.get("PoolGroup", "default", "serving")
            assert group.status.coordinated is True
            assert runtime.registry.gauge("poolgroup", "ratio_ok").get(
                "serving", "default"
            ) == 1.0
            assert runtime.registry.gauge(
                "poolgroup", "coordinated_total"
            ).get("serving", "default") >= 1.0
        finally:
            faults.uninstall()
            runtime.close()


class TestEventStormChaos:
    """ISSUE 14 acceptance: a seeded 1k-event churn storm inside one
    debounce window coalesces into a handful of event passes (not one
    per event), lands on the same fixed point as the tick-paced loop,
    keeps the self-SLO fast windows under threshold — and at 100%
    solver faults the event pass degrades through the same numpy
    ladder without ever blocking the watch callback thread."""

    FIXED_POINT = 11  # queue=41, AverageValue target=4 -> ceil(41/4)
    STORM = 1000

    def make_runtime(self, event_driven, event_thread=False):
        clock = FakeClock()
        provider = RecordingFactory()
        provider.node_replicas["g"] = 5
        runtime = KarpenterRuntime(
            Options(
                event_driven=event_driven,
                event_debounce_s=0.01,
                event_thread=event_thread,
                solver_health_threshold=2,
                solver_probe_interval_s=0.0,  # probe every dispatch
            ),
            cloud_provider_factory=provider,
            clock=clock,
        )
        # pin the XLA device path so solver faults hit a real dispatch
        runtime.solver_service.backend = "xla"
        pending_capacity_world(runtime.store)
        runtime.registry.register("queue", "length").set(
            "q", "default", 41.0
        )
        runtime.store.create(sng_of("g", replicas=5))
        runtime.store.create(
            queue_ha("g", 'karpenter_queue_length{name="q"}')
        )
        return runtime, provider, clock

    def drain(self, runtime, clock, limit=8):
        """The debounce thread's job, driven deterministically."""
        for _ in range(limit):
            if runtime.manager.dirty_count() == 0:
                return
            clock.advance(0.01)
            runtime.manager.run_event_pass()

    def settle(self, runtime, clock, n):
        for _ in range(n):
            clock.advance(61.0)
            runtime.manager.reconcile_all()

    def _storm(self, runtime):
        for i in range(self.STORM):
            runtime.store.create(Pod(
                metadata=ObjectMeta(name=f"storm-{i}"), spec=PodSpec()
            ))

    def test_storm_coalesces_matches_fixed_point_and_slo(self):
        # tick-paced comparator: same world, same storm, ticks only
        runtime, provider, clock = self.make_runtime(False)
        try:
            self._storm(runtime)
            self.settle(runtime, clock, 6)
            tick_fixed = provider.node_replicas["g"]
        finally:
            runtime.close()
        assert tick_fixed == self.FIXED_POINT

        runtime, provider, clock = self.make_runtime(True)
        manager = runtime.manager
        passes_gauge = runtime.registry.gauge(
            "runtime", "event_passes_total"
        )
        try:
            self.settle(runtime, clock, 2)
            self.drain(runtime, clock)
            before = passes_gauge.get("manager", "-") or 0.0
            self._storm(runtime)  # 1k events, ONE debounce window
            self.drain(runtime, clock)
            coalesced = (passes_gauge.get("manager", "-") or 0.0) - before
            assert 1 <= coalesced <= 4, (
                f"a 1k-event storm must coalesce into a handful of "
                f"passes (producer -> autoscaler -> node-group hops), "
                f"got {coalesced}"
            )
            self.settle(runtime, clock, 4)
            self.drain(runtime, clock)
            assert provider.node_replicas["g"] == tick_fixed, (
                "the event-driven fixed point must equal tick-paced"
            )
            # self-SLO fast windows under threshold: sub-second event
            # passes are exactly what the objective grades
            monitor = runtime.selfslo
            assert not monitor.tripped
            windows = monitor._last_eval["windows"]
            fast = [windows[w.name] for w in monitor.windows[:2]]
            assert not any(w["violating"] for w in fast), (
                f"fast burn windows must stay under threshold: {fast}"
            )
        finally:
            runtime.close()

    def test_storm_is_deterministic(self):
        """Same seed, same world -> identical pass/solve counts and
        actuation history: the storm is a replay, not a dice roll."""

        def run():
            runtime, provider, clock = self.make_runtime(True)
            try:
                self.settle(runtime, clock, 2)
                self.drain(runtime, clock)
                self._storm(runtime)
                self.drain(runtime, clock)
                self.settle(runtime, clock, 2)
                return (
                    list(provider.actuations),
                    runtime.registry.gauge(
                        "runtime", "event_passes_total"
                    ).get("manager", "-"),
                    runtime.solver_service.stats.requests,
                    provider.node_replicas["g"],
                )
            finally:
                runtime.close()

        assert run() == run()

    def test_total_solver_faults_degrade_without_blocking_watch(self):
        """100% device faults during the storm: the watch callback
        thread only marks dirty (returns in microseconds per event —
        no solve ever runs on it), the manager's REAL debounce thread
        drains the storm through the numpy ladder, and the backend FSM
        trips wholesale exactly as a tick-paced outage would."""
        import time as _t

        runtime, provider, clock = self.make_runtime(
            True, event_thread=True
        )
        service = runtime.solver_service
        try:
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan(
                "solver.dispatch", probability=1.0, code="DeviceFault"
            )
            t0 = _t.perf_counter()
            self._storm(runtime)
            callback_wall = _t.perf_counter() - t0
            assert callback_wall < 5.0, (
                f"1k watch callbacks took {callback_wall:.1f}s — the "
                f"callback thread must never run (or wait on) a solve"
            )
            # the event thread owns the passes: wait for it to drain
            # the storm through the degradation ladder
            deadline = _t.monotonic() + 30.0
            while _t.monotonic() < deadline:
                if (
                    runtime.manager.dirty_count() == 0
                    and service.queue_depth() == 0
                ):
                    break
                _t.sleep(0.02)
            assert runtime.manager.dirty_count() == 0, (
                "the debounce thread must drain the storm"
            )
            assert service.queue_depth() == 0
            assert registry.injected.get("solver.dispatch", 0) >= 1
            assert service.stats.fallbacks >= 1, (
                "event passes must degrade through the numpy ladder"
            )
            # the whole 1k-event storm coalesced into ONE failed
            # dispatch; follow-up event rounds accumulate the
            # CONSECUTIVE failures the wholesale FSM trip needs
            for n in range(4):
                runtime.store.create(Pod(
                    metadata=ObjectMeta(name=f"probe-{n}"),
                    spec=PodSpec(),
                ))
                deadline = _t.monotonic() + 10.0
                while _t.monotonic() < deadline:
                    if runtime.manager.dirty_count() == 0:
                        break
                    _t.sleep(0.02)
            assert service.backend_health() == "degraded", (
                "consecutive device faults must trip the FSM wholesale"
            )
        finally:
            faults.uninstall()
            runtime.close()


class TestSelfSLOChaos:
    """ISSUE 12 acceptance: a seeded chaos run at 100% solver faults
    drives the self-SLO fast-burn window over threshold, emits the
    `selfslo_burn` flight-recorder dump (trip-class machinery,
    observability/selfslo.py), and RECOVERS budget after faults clear —
    the control plane detecting its own degradation, not a human."""

    def test_fast_burn_trips_dumps_and_recovers(self, tmp_path):
        from karpenter_tpu.observability import (
            default_flight_recorder,
            reset_default_flight_recorder,
            set_default_flight_recorder,
        )

        saved_recorder = default_flight_recorder()
        reset_default_flight_recorder()
        clock = FakeClock()
        # plain FakeFactory: --journal-dir fences actuations with a
        # token, which the recording subclass's narrower signature
        # doesn't carry (actuation accounting isn't this scenario's
        # concern)
        provider = FakeFactory()
        provider.node_replicas["g"] = 5
        runtime = KarpenterRuntime(
            Options(
                solver_health_threshold=2,
                solver_probe_interval_s=0.0,
                journal_dir=str(tmp_path / "journal"),
            ),
            cloud_provider_factory=provider,
            clock=clock,
        )
        runtime.solver_service.backend = "xla"
        runtime.registry.register("queue", "length").set(
            "q", "default", 41.0
        )
        runtime.store.create(sng_of("g", replicas=5))
        runtime.store.create(
            queue_ha("g", 'karpenter_queue_length{name="q"}')
        )
        pending_capacity_world(runtime.store)
        monitor = runtime.selfslo
        service = runtime.solver_service

        def tick(n):
            # cluster churn (TestChaosScenario.tick): a toggling pod
            # defeats the encode memo so EVERY tick drives a real solve
            # through the service — the surface the faults poison
            for _ in range(n):
                try:
                    runtime.store.delete("Pod", "default", "churn-pod")
                except KeyError:
                    runtime.store.create(Pod(
                        metadata=ObjectMeta(name="churn-pod"),
                        spec=PodSpec(),
                    ))
                clock.advance(10.0)
                runtime.manager.reconcile_all()

        try:
            # healthy warm-up: the budget starts full
            tick(10)
            assert not monitor.tripped
            assert runtime.registry.gauge(
                "selfslo", "budget_remaining"
            ).get("5m", "-") == 1.0

            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan("solver.dispatch", probability=1.0)
            tick(40)
            assert service.backend_health() == "degraded"
            assert monitor.tripped, (
                "100% solver faults must drive the fast-burn pair "
                "over threshold"
            )
            fast = monitor._last_eval["windows"]["5m"]
            assert fast["burn_rate"] > 14.4
            assert fast["budget_remaining"] < 1.0
            burns = [
                e for e in runtime.flight_recorder.events()
                if e["kind"] == "selfslo_burn"
            ]
            assert len(burns) == 1, "one incident, one burn event"
            dumps = [
                p.name for p in (tmp_path / "journal").iterdir()
                if p.name.startswith("flightrecorder-")
                and "selfslo_burn" in p.name
            ]
            assert dumps, (
                "the selfslo_burn trip must auto-dump the ring into "
                "--journal-dir"
            )

            faults.uninstall()  # ---- faults clear ----
            tick(60)
            assert service.backend_health() == "healthy"
            assert not monitor.tripped, "the trip must re-arm"
            recovered = monitor._last_eval["windows"]["5m"]
            assert recovered["burn_rate"] < 14.4
            assert recovered["budget_remaining"] > 0.9, (
                "budget must RECOVER once bad events age out of the "
                "sliding window"
            )
            assert monitor.trips_total == 1
        finally:
            faults.uninstall()
            runtime.close()
            set_default_flight_recorder(saved_recorder)


class TestConstraintChaos:
    """PR 16 satellite: 100% faults on `constraints.mask` must NEVER
    block the signal — every tick degrades to the unconstrained-but-
    feasible wire with the fallback counted and the breaker FSM fed
    (closed -> open -> short-circuit), and clearing the faults recovers
    the constrained fixed point."""

    def make_runtime(self):
        from karpenter_tpu.api.core import (
            Container, RESERVATION_LABEL, ZONE_LABEL,
        )
        from karpenter_tpu.constraints import ConstraintGroup, SpreadSpec

        clock = FakeClock()
        runtime = KarpenterRuntime(
            Options(),
            cloud_provider_factory=FakeFactory(),
            clock=clock,
        )
        store = runtime.store
        for zone in ("z1", "z2"):
            store.create(Node(
                metadata=ObjectMeta(
                    name=f"{zone}-n0",
                    labels={"pool": "serving", ZONE_LABEL: zone},
                ),
                spec=NodeSpec(),
                status=NodeStatus(
                    allocatable=resource_list(
                        cpu="8", memory="32Gi", pods="32"
                    ),
                    conditions=[NodeCondition("Ready", "True")],
                ),
            ))
        store.create(Node(
            metadata=ObjectMeta(
                name="reserved-0",
                labels={"pool": "reserved", RESERVATION_LABEL: "gold"},
            ),
            spec=NodeSpec(),
            status=NodeStatus(
                allocatable=resource_list(
                    cpu="8", memory="32Gi", pods="32"
                ),
                conditions=[NodeCondition("Ready", "True")],
            ),
        ))
        for zone, cons in (("z1", True), ("z2", False)):
            store.create(MetricsProducer(
                metadata=ObjectMeta(name=f"serving-{zone}"),
                spec=MetricsProducerSpec(
                    pending_capacity=PendingCapacitySpec(
                        node_selector={
                            "pool": "serving", ZONE_LABEL: zone
                        },
                        constraints=[
                            ConstraintGroup(
                                name="web",
                                pod_selector={"app": "web"},
                                spread=SpreadSpec(),
                            ),
                            ConstraintGroup(
                                name="gold",
                                pod_selector={"tier": "gold"},
                                reservation="gold",
                            ),
                        ] if cons else [],
                    )
                ),
            ))
        store.create(MetricsProducer(
            metadata=ObjectMeta(name="serving-reserved"),
            spec=MetricsProducerSpec(
                pending_capacity=PendingCapacitySpec(
                    node_selector={"pool": "reserved"},
                )
            ),
        ))
        for i in range(4):
            store.create(Pod(
                metadata=ObjectMeta(
                    name=f"web-{i}", labels={"app": "web"}
                ),
                spec=PodSpec(node_name="", containers=[Container(
                    requests=resource_list(cpu="1", memory="1Gi")
                )]),
            ))
        store.create(Pod(
            metadata=ObjectMeta(name="gold-0", labels={"tier": "gold"}),
            spec=PodSpec(node_name="", containers=[Container(
                requests=resource_list(cpu="1", memory="1Gi")
            )]),
        ))
        return runtime, clock

    def tick(self, runtime, clock, n=1):
        """Churned ticks: the producer memo rightly short-circuits an
        unchanged cluster and a memo hit never reaches the encoder's
        fault point, so each tick toggles a pod."""
        for _ in range(n):
            try:
                runtime.store.delete("Pod", "default", "churn-pod")
            except KeyError:
                runtime.store.create(Pod(
                    metadata=ObjectMeta(name="churn-pod"),
                    spec=PodSpec(),
                ))
            clock.advance(61.0)
            runtime.manager.reconcile_all()

    def _pending(self, runtime, name):
        status = runtime.store.get(
            "MetricsProducer", "default", name
        ).status.pending_capacity
        return status.pending_pods if status else -1

    def test_mask_faults_never_block_then_recover(self):
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            encoder as E,
        )
        from karpenter_tpu.resilience import CircuitBreaker

        runtime, clock = self.make_runtime()
        saved_breaker = E._constraint_breaker
        E.reset_constraint_state()
        # the module breaker's reset window runs on REAL monotonic
        # time; pin it to the scenario clock so the open window (and
        # the recovery probe) replay deterministically
        E._constraint_breaker = CircuitBreaker(
            failure_threshold=3, reset_s=1000.0, clock=clock
        )
        try:
            # ---- the constrained fixed point ----
            self.tick(runtime, clock, 2)
            stats = E.constraint_stats
            assert stats["compiles"] >= 1
            assert stats["fallbacks"] == 0
            assert not stats["degraded"]
            skew = runtime.registry.gauge(
                "constraints", "spread_skew"
            ).get("web", "-")
            assert skew == 0.0  # 4 web pods spread 2/2
            assert runtime.registry.gauge(
                "constraints", "reservation_fill"
            ).get("gold", "-") == 1.0
            assert self._pending(runtime, "serving-reserved") == 1
            fixed_point = {
                name: self._pending(runtime, name)
                for name in ("serving-z1", "serving-z2",
                             "serving-reserved")
            }

            # ---- 100% mask faults ----
            registry = faults.install(FaultRegistry(seed=CHAOS_SEED))
            registry.plan(
                "constraints.mask", mode="error", probability=1.0
            )
            self.tick(runtime, clock, 6)
            stats = E.constraint_stats
            assert stats["degraded"]
            assert stats["fallbacks"] >= 6, (
                "every churned tick must fall back, not block"
            )
            # the breaker FSM was fed: 3 failures trip it open and the
            # remaining ticks short-circuit without re-probing the
            # faulty compile path
            assert stats["short_circuits"] >= 1
            assert runtime.registry.gauge(
                "constraints", "breaker_state"
            ).get("-", "-") == 1.0
            assert runtime.registry.gauge(
                "constraints", "fallback_total"
            ).get("-", "-") == float(stats["fallbacks"])
            # never-block: the unconstrained-but-feasible wire keeps
            # publishing a live signal for every producer
            total = sum(
                self._pending(runtime, name)
                for name in ("serving-z1", "serving-z2",
                             "serving-reserved")
            )
            assert total >= 5, "all pods still placed somewhere"
            assert self._pending(runtime, "serving-z1") >= 0

            # ---- faults clear ----
            faults.uninstall()
            clock.advance(1000.0)  # past the breaker's open window
            self.tick(runtime, clock, 2)
            stats = E.constraint_stats
            assert not stats["degraded"], (
                "the degraded-epoch fingerprint must retry the compile "
                "and converge back"
            )
            assert runtime.registry.gauge(
                "constraints", "breaker_state"
            ).get("-", "-") == 0.0
            recovered = {
                name: self._pending(runtime, name)
                for name in ("serving-z1", "serving-z2",
                             "serving-reserved")
            }
            assert recovered == fixed_point, (
                "clearing faults must restore the constrained verdicts"
            )
            assert runtime.registry.gauge(
                "constraints", "reservation_fill"
            ).get("gold", "-") == 1.0
        finally:
            faults.uninstall()
            E._constraint_breaker = saved_breaker
            E.reset_constraint_state()
            runtime.close()
