"""The numpy degraded-mode backend must equal the XLA program exactly.

ops/numpy_binpack.py re-lays-out the solve for CPUs (sparse O(P)
scatters where the XLA program uses dense MXU-shaped reductions); every
int output must match the XLA backend element for element across the
full operand space — weights, forbidden masks, preference scores,
zero-allocatable groups, empty fleets. Same pinning discipline as
tests/test_pallas_binpack.py applies to the pallas backend.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from karpenter_tpu.ops.binpack import BinPackInputs, binpack, solve
from karpenter_tpu.ops.numpy_binpack import binpack_numpy


def random_inputs(
    seed,
    pods=257,
    groups=19,
    resources=3,
    taints=8,
    labels=8,
    with_weight=True,
    with_forbidden=False,
    with_score=False,
    with_exclusive=False,
):
    rng = np.random.default_rng(seed)
    inputs = BinPackInputs(
        pod_requests=rng.uniform(0.0, 8.0, (pods, resources)).astype(
            np.float32
        ),
        pod_valid=rng.random(pods) < 0.95,
        pod_intolerant=rng.random((pods, taints)) < 0.2,
        pod_required=rng.random((pods, labels)) < 0.15,
        group_allocatable=np.where(
            rng.random((groups, resources)) < 0.1,
            0.0,
            rng.uniform(2.0, 16.0, (groups, resources)),
        ).astype(np.float32),
        group_taints=rng.random((groups, taints)) < 0.2,
        group_labels=rng.random((groups, labels)) < 0.7,
        pod_weight=(
            rng.integers(1, 50, pods).astype(np.int32)
            if with_weight
            else None
        ),
        pod_group_forbidden=(
            rng.random((pods, groups)) < 0.3 if with_forbidden else None
        ),
        pod_group_score=(
            rng.integers(0, 100, (pods, groups)).astype(np.float32)
            if with_score
            else None
        ),
        pod_exclusive=(
            rng.random(pods) < 0.3 if with_exclusive else None
        ),
    )
    return inputs


def assert_equal(out_np, out_xla):
    np.testing.assert_array_equal(
        np.asarray(out_np.assigned), np.asarray(out_xla.assigned)
    )
    np.testing.assert_array_equal(
        np.asarray(out_np.assigned_count),
        np.asarray(out_xla.assigned_count),
    )
    np.testing.assert_array_equal(
        np.asarray(out_np.nodes_needed), np.asarray(out_xla.nodes_needed)
    )
    np.testing.assert_array_equal(
        np.asarray(out_np.lp_bound), np.asarray(out_xla.lp_bound)
    )
    assert int(out_np.unschedulable) == int(out_xla.unschedulable)


class TestEquality:
    @pytest.mark.parametrize("seed", range(12))
    def test_weighted_random_fleets(self, seed):
        inputs = random_inputs(seed)
        assert_equal(
            binpack_numpy(inputs, buckets=16), binpack(inputs, buckets=16)
        )

    @pytest.mark.parametrize("seed", range(6))
    def test_affinity_masks_and_scores(self, seed):
        inputs = random_inputs(
            seed + 100, with_forbidden=True, with_score=True
        )
        assert_equal(
            binpack_numpy(inputs, buckets=16), binpack(inputs, buckets=16)
        )

    @pytest.mark.parametrize("seed", range(6))
    def test_exclusive_rows(self, seed):
        """pod_exclusive (hostname self-anti-affinity) forces bucket=B
        identically in both backends, alone and with every other
        operand."""
        inputs = random_inputs(
            seed + 400,
            with_exclusive=True,
            with_forbidden=(seed % 2 == 0),
            with_score=(seed % 3 == 0),
        )
        assert_equal(
            binpack_numpy(inputs, buckets=16), binpack(inputs, buckets=16)
        )
        # semantics: a group's node count covers its exclusive weight
        out = binpack(inputs, buckets=16)
        assigned = np.asarray(out.assigned)
        excl = np.asarray(inputs.pod_exclusive)
        w = np.asarray(inputs.pod_weight)
        for t in range(inputs.group_allocatable.shape[0]):
            rows = (assigned == t) & excl
            assert int(out.nodes_needed[t]) >= int(w[rows].sum())

    @pytest.mark.parametrize("seed", range(6))
    def test_unweighted_and_forbidden_only(self, seed):
        inputs = random_inputs(
            seed + 200, with_weight=False, with_forbidden=True
        )
        assert_equal(
            binpack_numpy(inputs, buckets=32), binpack(inputs, buckets=32)
        )

    def test_empty_fleet(self):
        inputs = random_inputs(0, pods=0)
        out = binpack_numpy(inputs, buckets=8)
        assert out.assigned.shape == (0,)
        assert int(out.unschedulable) == 0
        assert_equal(out, binpack(inputs, buckets=8))

    def test_everything_unschedulable(self):
        inputs = random_inputs(3)
        inputs = dataclasses.replace(
            inputs,
            pod_group_forbidden=np.ones(
                (
                    inputs.pod_requests.shape[0],
                    inputs.group_allocatable.shape[0],
                ),
                bool,
            ),
        )
        assert_equal(
            binpack_numpy(inputs, buckets=8), binpack(inputs, buckets=8)
        )

    def test_fit_boundary_shares(self):
        """Requests exactly at allocatable (share == 1.0) and at bucket
        boundaries: quantization must agree at the edges."""
        rng = np.random.default_rng(7)
        groups, buckets = 5, 16
        alloc = rng.uniform(4.0, 16.0, (groups, 3)).astype(np.float32)
        # pods sized to exact fractions of group 0's allocatable
        fractions = np.array(
            [1.0, 0.5, 1.0 / 16, 3.0 / 16, 0.999, 1.001], np.float32
        )
        requests = np.outer(fractions, alloc[0]).astype(np.float32)
        inputs = BinPackInputs(
            pod_requests=requests,
            pod_valid=np.ones(len(fractions), bool),
            pod_intolerant=np.zeros((len(fractions), 4), bool),
            pod_required=np.zeros((len(fractions), 4), bool),
            group_allocatable=alloc,
            group_taints=np.zeros((groups, 4), bool),
            group_labels=np.ones((groups, 4), bool),
        )
        assert_equal(
            binpack_numpy(inputs, buckets=buckets),
            binpack(inputs, buckets=buckets),
        )


class TestDispatcher:
    def test_auto_on_cpu_routes_to_numpy(self, monkeypatch):
        """The degraded mode: a CPU default backend solves via the
        numpy program (tests run on the virtual CPU mesh, so plain
        auto IS the numpy path here)."""
        import jax

        assert jax.default_backend() == "cpu"
        calls = {}
        from karpenter_tpu.ops import numpy_binpack

        real = numpy_binpack.binpack_numpy

        def spy(inputs, buckets=32):
            calls["hit"] = True
            return real(inputs, buckets=buckets)

        monkeypatch.setattr(numpy_binpack, "binpack_numpy", spy)
        inputs = random_inputs(5)
        out = solve(inputs, buckets=8, backend="auto")
        assert calls.get("hit")
        assert_equal(out, binpack(inputs, buckets=8))

    def test_explicit_backends_still_reachable(self):
        inputs = random_inputs(6)
        assert_equal(
            solve(inputs, buckets=8, backend="numpy"),
            solve(inputs, buckets=8, backend="xla"),
        )


class TestLpBoundContract:
    def test_lp_bound_within_one_at_f32_boundaries(self):
        """The ONE documented parity exception: at demand/allocatable
        ratios where one f32 ulp exceeds the -1e-5 ceil guard, the numpy
        path's f64 demand accumulation may legitimately differ from the
        XLA f32 einsum by +-1 — never more. (Everything else stays
        exactly equal even here.)"""
        rng = np.random.default_rng(11)
        pods, groups = 8192, 3
        alloc = np.full((groups, 3), 1000.0, np.float32)
        # demand sums land near integer multiples of allocatable
        requests = rng.uniform(0.4, 0.6, (pods, 3)).astype(np.float32)
        inputs = BinPackInputs(
            pod_requests=requests,
            pod_valid=np.ones(pods, bool),
            pod_intolerant=np.zeros((pods, 4), bool),
            pod_required=np.zeros((pods, 4), bool),
            group_allocatable=alloc,
            group_taints=np.zeros((groups, 4), bool),
            group_labels=np.ones((groups, 4), bool),
        )
        out_np = binpack_numpy(inputs, buckets=16)
        out_xla = binpack(inputs, buckets=16)
        np.testing.assert_array_equal(
            np.asarray(out_np.assigned), np.asarray(out_xla.assigned)
        )
        np.testing.assert_array_equal(
            np.asarray(out_np.assigned_count),
            np.asarray(out_xla.assigned_count),
        )
        np.testing.assert_array_equal(
            np.asarray(out_np.nodes_needed),
            np.asarray(out_xla.nodes_needed),
        )
        diff = np.abs(
            np.asarray(out_np.lp_bound, np.int64)
            - np.asarray(out_xla.lp_bound, np.int64)
        )
        assert diff.max() <= 1


class TestProducerFetchBranch:
    def test_solve_pending_through_xla_device_outputs(self):
        """The producer's packed device->host fetch (_dispatch_and_record
        jax.Array branch) must stay covered now that auto routes to
        numpy on the CPU suite: force the XLA backend through the full
        solve_pending path and compare against the numpy-backend run."""
        import functools

        from karpenter_tpu.metrics.producers.pendingcapacity import (
            solve_pending,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.store import Store
        from tests.test_pendingcapacity import (
            pending_mp,
            pending_pod,
            ready_node,
        )

        def run(backend):
            store = Store()
            store.create(ready_node("n", {"group": "a"}, cpu="4"))
            store.create(pending_mp("group-a", {"group": "a"}))
            for i in range(5):
                store.create(pending_pod(f"p{i}", cpu="2", memory="1Gi"))
            mps = [
                mp for mp in store.list("MetricsProducer")
                if mp.spec.pending_capacity is not None
            ]
            solve_pending(
                store, mps, GaugeRegistry(),
                solver=functools.partial(solve, backend=backend),
            )
            status = mps[0].status.pending_capacity
            return (
                status.pending_pods,
                status.additional_nodes_needed,
                status.unschedulable_pods,
            )

        assert run("xla") == run("numpy") == (5, 3, 0)


class TestNativeKernel:
    """The C kernel (native/binpack_kernel.c) and the pure-numpy stages
    must be interchangeable: same outputs on every operand combination,
    whichever one a host's toolchain situation selects."""

    @pytest.mark.parametrize("seed", range(6))
    def test_native_equals_fallback(self, seed):
        from karpenter_tpu.native import load_kbinpack

        if load_kbinpack() is None:
            pytest.skip("no C toolchain")
        inputs = random_inputs(
            seed + 300, with_forbidden=(seed % 2 == 0),
            with_score=(seed % 3 == 0), with_exclusive=(seed % 2 == 1),
        )
        assert_equal(
            binpack_numpy(inputs, buckets=16, use_native=True),
            binpack_numpy(inputs, buckets=16, use_native=False),
        )

    def test_native_equals_xla_with_all_operands(self):
        from karpenter_tpu.native import load_kbinpack

        if load_kbinpack() is None:
            pytest.skip("no C toolchain")
        inputs = random_inputs(
            7, pods=997, taints=70, labels=70,  # >64: multi-word bitsets
            with_forbidden=True, with_score=True, with_exclusive=True,
        )
        assert_equal(
            binpack_numpy(inputs, buckets=32, use_native=True),
            binpack(inputs, buckets=32),
        )


class TestThreadedAssign:
    """karpenter_assign_mt: the choice phase fans out over threads, every
    aggregate accumulates sequentially in pod order — outputs must be
    BITWISE identical to the fused single pass for any thread count and
    any operand mix (score/forbidden/weight/exclusive)."""

    @pytest.mark.parametrize("threads", [2, 3, 8])
    def test_bitwise_equal_to_single_pass(self, monkeypatch, threads):
        from karpenter_tpu.native import load_kbinpack
        from karpenter_tpu.ops import numpy_binpack as nb

        lib = load_kbinpack()
        if lib is None or not hasattr(lib, "karpenter_assign_mt"):
            pytest.skip("native mt kernel unavailable")
        rng = np.random.default_rng(23)
        for case in range(12):
            P, T = int(rng.integers(1, 400)), int(rng.integers(1, 24))
            K, L = int(rng.integers(1, 100)), int(rng.integers(1, 100))
            args = dict(
                requests=rng.uniform(0, 2, (P, 4)).astype(np.float32),
                valid=rng.random(P) < 0.9,
                intolerant=rng.random((P, K)) < 0.1,
                required=rng.random((P, L)) < 0.1,
                alloc=rng.uniform(0, 4, (T, 4)).astype(np.float32),
                taints=rng.random((T, K)) < 0.2,
                labels=rng.random((T, L)) < 0.8,
                # independent coin flips: score+forbidden TOGETHER (the
                # argmax-with-mask branch) must occur, not just each alone
                forbidden=(
                    rng.random((P, T)) < 0.2 if rng.random() < 0.5 else None
                ),
                score=(
                    rng.normal(size=(P, T)).astype(np.float32)
                    if rng.random() < 0.5
                    else None
                ),
                weight=(
                    rng.integers(1, 9, P).astype(np.int64)
                    if rng.random() < 0.5
                    else None
                ),
                exclusive=(
                    rng.random(P) < 0.1 if rng.random() < 0.5 else None
                ),
                buckets=int(rng.integers(2, 33)),
            )
            monkeypatch.setenv("KARPENTER_SOLVER_THREADS", "1")
            single = nb._assign_native(lib, **args)
            monkeypatch.setenv("KARPENTER_SOLVER_THREADS", str(threads))
            multi = nb._assign_native(lib, **args)
            for s, m in zip(single[:4], multi[:4]):
                np.testing.assert_array_equal(np.asarray(s), np.asarray(m))
            assert single[4] == multi[4], case


class TestPackBits:
    """The C octet-gather packer vs the pure-numpy np.packbits fallback:
    identical words at every adversarial width (word boundaries, single
    column, sub-octet tails, non-contiguous views)."""

    def test_c_pack_equals_numpy_pack(self):
        from karpenter_tpu.native import load_kbinpack
        from karpenter_tpu.ops.numpy_binpack import _pack_bits

        lib = load_kbinpack()
        if lib is None:
            pytest.skip("native packer unavailable")
        rng = np.random.default_rng(5)
        widths = [1, 2, 7, 8, 9, 63, 64, 65, 127, 128, 129, 200]
        for k in widths:
            for n in (0, 1, 3, 257):
                matrix = rng.random((n, k)) < 0.4
                np.testing.assert_array_equal(
                    _pack_bits(matrix, lib),
                    _pack_bits(matrix, None),
                    err_msg=f"n={n} k={k}",
                )
        # non-contiguous view (every other row): the C path must copy,
        # not read strided memory as if dense
        big = rng.random((64, 70)) < 0.5
        view = big[::2]
        assert not view.flags.c_contiguous
        np.testing.assert_array_equal(
            _pack_bits(view, lib), _pack_bits(np.ascontiguousarray(view), None)
        )
        # int storage (not bool): the packer must see 0/1 bytes
        ints = (rng.random((5, 66)) < 0.5).astype(np.int64)
        np.testing.assert_array_equal(
            _pack_bits(ints, lib), _pack_bits(ints != 0, None)
        )
