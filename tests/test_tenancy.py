"""Multi-tenant control plane tests (docs/multitenancy.md).

The load-bearing pins:

  * PARITY — a cross-tenant concatenated dispatch is bit-identical to N
    independent per-tenant dispatches on every output field, for the
    decide, cost, and forecast families, on BOTH the device (xla) and
    numpy paths (the kernels are row-independent; the concat/scatter
    helpers must keep them that way).
  * ISOLATION — a tenant at 100% injected faults degrades ALONE: its
    rows serve from the bit-identical numpy mirror, its breaker opens,
    and every tenant's lockstep fixed point (including the faulted
    one's, since the mirror is bit-identical) equals the no-fault run.
  * FAIRNESS — deficit-weighted admission: oversized tenants dispatch
    alone, deferred tenants carry credit, shares converge to weights.
  * the per-tenant registry: stack namespacing, per-tenant fencing
    independence, and karpenter_tenant_* retirement on deletion;
  * the pluggable pricing feed (--pricing-file): mtime reload,
    never-block on a broken file, per-tenant sources via the registry;
  * per-metric SLO targets (spec.behavior.slo.metrics) feeding
    worst-case risk;
  * the non-slow batched-vs-sequential regression guard (`make
    bench-multitenant` publishes the full 1k-tenant numbers).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np
import pytest

from karpenter_tpu.faults import injected_faults
from karpenter_tpu.forecast import models as FM
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.ops import cost as CK
from karpenter_tpu.ops import decision as D
from karpenter_tpu.simulate import (
    multitenant_cost_inputs,
    multitenant_fleet_inputs,
    simulate_multitenant,
)
from karpenter_tpu.solver import SolverService
from karpenter_tpu.tenancy import (
    MultiTenantScheduler,
    TenantBreakerBoard,
    TenantRegistry,
    TenantSpec,
    WeightedAdmission,
    load_tenant_config,
)
from karpenter_tpu.tenancy.scheduler import (
    concat_cost_inputs,
    concat_decision_inputs,
    slice_cost_outputs,
)

from test_observability import _lint_exposition


def random_decide_inputs(
    seed: int, n: int = 6, m: int = 2, k: int = 1,
    now: float = 1000.0, forecast: bool = False,
) -> D.DecisionInputs:
    """A random one-tenant fleet: mixed target types, some invalid
    metrics, random windows/policies — the adversarial shape for the
    row-independence claim."""
    rng = np.random.RandomState(seed)
    spec = rng.randint(0, 50, n).astype(np.int32)
    d = dict(
        metric_value=rng.uniform(0, 200, (n, m)).astype(np.float32),
        target_value=rng.choice([0.0, 2.0, 8.0], (n, m)).astype(
            np.float32
        ),
        target_type=rng.randint(0, 4, (n, m)).astype(np.int32),
        metric_valid=rng.rand(n, m) > 0.2,
        spec_replicas=spec,
        status_replicas=np.clip(
            spec + rng.randint(-2, 3, n), 0, None
        ).astype(np.int32),
        min_replicas=rng.randint(0, 3, n).astype(np.int32),
        max_replicas=(spec + rng.randint(1, 100, n)).astype(np.int32),
        up_window=rng.choice([0, 60], n).astype(np.int32),
        down_window=rng.choice([0, 300], n).astype(np.int32),
        up_policy=rng.randint(0, 3, n).astype(np.int32),
        down_policy=rng.randint(0, 3, n).astype(np.int32),
        last_scale_time=rng.uniform(0, 900, n).astype(np.float32),
        has_last_scale=rng.rand(n) > 0.5,
        now=np.float32(now),
        up_ptype=rng.randint(0, 2, (n, k)).astype(np.int32),
        up_pvalue=rng.randint(1, 20, (n, k)).astype(np.int32),
        up_pperiod=rng.randint(1, 600, (n, k)).astype(np.int32),
        up_pvalid=rng.rand(n, k) > 0.5,
        down_ptype=rng.randint(0, 2, (n, k)).astype(np.int32),
        down_pvalue=rng.randint(1, 20, (n, k)).astype(np.int32),
        down_pperiod=rng.randint(1, 600, (n, k)).astype(np.int32),
        down_pvalid=rng.rand(n, k) > 0.5,
    )
    if forecast:
        d["forecast_value"] = rng.uniform(0, 300, (n, m)).astype(
            np.float32
        )
        d["forecast_valid"] = rng.rand(n, m) > 0.3
    return D.DecisionInputs(**d)


def random_cost_inputs(seed: int, n: int = 6, m: int = 2) -> CK.CostInputs:
    rng = np.random.RandomState(seed)
    base = rng.randint(0, 100, n).astype(np.int32)
    return CK.CostInputs(
        base_desired=base,
        min_replicas=rng.randint(0, 5, n).astype(np.int32),
        max_replicas=(base + rng.randint(0, 300, n)).astype(np.int32),
        unit_cost=rng.choice([0.0, 0.07, 1.7, 12.5], n).astype(
            np.float32
        ),
        slo_weight=rng.choice([0.0, 1.0, 333.3], n).astype(np.float32),
        max_hourly_cost=rng.choice([0.0, 2.0, 55.5], n).astype(
            np.float32
        ),
        slo_valid=rng.rand(n) > 0.3,
        slo_target=rng.uniform(0.5, 10, (n, m)).astype(np.float32),
        demand_mu=rng.uniform(0, 500, (n, m)).astype(np.float32),
        demand_sigma=rng.choice([0.0, 3.0, 25.0], (n, m)).astype(
            np.float32
        ),
        demand_valid=rng.rand(n, m) > 0.2,
    )


def random_forecast_inputs(seed: int, s: int = 4, t: int = 20):
    rng = np.random.RandomState(seed)
    return FM.ForecastInputs(
        values=rng.uniform(0, 100, (s, t)).astype(np.float32),
        valid=rng.rand(s, t) > 0.1,
        times=(
            -np.arange(t, dtype=np.float32)[::-1][None, :].repeat(s, 0)
            * 10.0
        ),
        weights=rng.uniform(0.1, 1.0, (s, t)).astype(np.float32),
        horizon=np.full(s, 60.0, np.float32),
        step_s=np.full(s, 10.0, np.float32),
        model=rng.randint(0, 2, s).astype(np.int32),
        season=np.zeros(s, np.int32),
        alpha=np.full(s, 0.5, np.float32),
        beta=np.full(s, 0.1, np.float32),
        gamma=np.full(s, 0.3, np.float32),
    )


def make_world(n_tenants: int = 4, weights=None, **scheduler_kw):
    """(service, registry, scheduler) with gauges in a fresh registry."""
    service = SolverService(registry=GaugeRegistry())
    metrics_registry = GaugeRegistry()
    registry = TenantRegistry(
        service=service, registry=metrics_registry,
        specs=[
            TenantSpec(
                id=f"t{i}",
                weight=(weights[i] if weights else 1.0),
            )
            for i in range(n_tenants)
        ],
    )
    scheduler = MultiTenantScheduler(registry, service, **scheduler_kw)
    return service, registry, scheduler


def assert_outputs_equal(kind, got, want, context=""):
    for f in dataclasses.fields(kind):
        a = np.asarray(getattr(got, f.name))
        b = np.asarray(getattr(want, f.name))
        assert np.array_equal(a, b), f"{context}.{f.name}: {a} != {b}"


class TestConcatParity:
    """The tentpole pin: concatenated slices == independent dispatches,
    bit for bit, device and numpy paths."""

    @pytest.mark.parametrize("backend", ["xla", "numpy"])
    def test_cost_concat_matches_independent(self, backend):
        service, _reg, scheduler = make_world(5)
        try:
            batch = {
                f"t{i}": random_cost_inputs(i, n=3 + i, m=1 + i % 3)
                for i in range(5)
            }
            out = scheduler.cost_all(batch, backend=backend)
            for tid, inputs in batch.items():
                indep = service.cost(inputs, backend=backend)
                assert_outputs_equal(
                    CK.CostOutputs, out[tid], indep, f"{backend}:{tid}"
                )
        finally:
            service.close()

    def test_cost_concat_matches_numpy_mirror_directly(self):
        """The host-path parity pin without the service in the loop:
        concat -> cost_numpy -> slice == per-tenant cost_numpy."""
        batch = [random_cost_inputs(40 + i, n=4, m=2) for i in range(4)]
        host = CK.cost_numpy(concat_cost_inputs(batch))
        offset = 0
        for i, inputs in enumerate(batch):
            n = int(inputs.base_desired.shape[0])
            mine = slice_cost_outputs(host, offset, offset + n)
            offset += n
            assert_outputs_equal(
                CK.CostOutputs, mine, CK.cost_numpy(inputs), f"t{i}"
            )

    def test_decide_concat_matches_independent(self):
        service, _reg, scheduler = make_world(6)
        try:
            batch = {
                f"t{i}": random_decide_inputs(
                    i, n=3 + i, m=1 + i % 3, k=1 + i % 2,
                    forecast=(i % 2 == 0),
                )
                for i in range(6)
            }
            out = scheduler.decide_all(batch)
            for tid, inputs in batch.items():
                assert_outputs_equal(
                    D.DecisionOutputs, out[tid], service.decide(inputs),
                    tid,
                )
        finally:
            service.close()

    def test_decide_groups_by_now_epoch(self):
        """Tenants at different now epochs must not concatenate (the
        stabilization math is epoch-relative); each group still comes
        back bit-identical to its independent dispatch."""
        service, _reg, scheduler = make_world(4)
        try:
            batch = {
                f"t{i}": random_decide_inputs(
                    i, now=1000.0 + 500.0 * (i % 2)
                )
                for i in range(4)
            }
            out = scheduler.decide_all(batch)
            assert scheduler.stats.decide_dispatches == 2
            for tid, inputs in batch.items():
                assert_outputs_equal(
                    D.DecisionOutputs, out[tid], service.decide(inputs),
                    tid,
                )
        finally:
            service.close()

    def test_concat_mixed_now_raises(self):
        with pytest.raises(ValueError):
            concat_decision_inputs(
                [
                    random_decide_inputs(0, now=1.0),
                    random_decide_inputs(1, now=2.0),
                ]
            )

    @pytest.mark.parametrize("backend", ["xla", "numpy"])
    def test_forecast_concat_matches_independent(self, backend):
        service, _reg, scheduler = make_world(3)
        try:
            batch = {
                f"t{i}": random_forecast_inputs(i, s=2 + i, t=12 + 4 * i)
                for i in range(3)
            }
            out = scheduler.forecast_all(batch, backend=backend)
            for tid, inputs in batch.items():
                indep = service.forecast(inputs, backend=backend)
                assert_outputs_equal(
                    FM.ForecastOutputs, out[tid], indep,
                    f"{backend}:{tid}",
                )
        finally:
            service.close()

    def test_solve_all_rides_the_coalescing_queue(self):
        """Cross-tenant bin-packs answer through the existing queue and
        match direct numpy solves (CPU resolution) per tenant."""
        from karpenter_tpu.ops.binpack import BinPackInputs
        from karpenter_tpu.ops.numpy_binpack import binpack_numpy

        rng = np.random.RandomState(0)
        service, _reg, scheduler = make_world(3)
        try:
            batch = {}
            for i in range(3):
                batch[f"t{i}"] = BinPackInputs(
                    pod_requests=rng.uniform(
                        0.1, 2.0, (8, 2)
                    ).astype(np.float32),
                    pod_valid=np.ones(8, bool),
                    pod_intolerant=np.zeros((8, 1), bool),
                    pod_required=np.zeros((8, 1), bool),
                    group_allocatable=rng.uniform(
                        4.0, 16.0, (3, 2)
                    ).astype(np.float32),
                    group_taints=np.zeros((3, 1), bool),
                    group_labels=np.zeros((3, 1), bool),
                )
            out = scheduler.solve_all(batch, buckets=8)
            assert scheduler.stats.solve_requests == 3
            for tid, inputs in batch.items():
                want = binpack_numpy(inputs, buckets=8)
                np.testing.assert_array_equal(
                    np.asarray(out[tid].assigned_count),
                    np.asarray(want.assigned_count),
                    err_msg=tid,
                )
        finally:
            service.close()


class TestIsolationChaos:
    """The chaos pin: one tenant at 100% faults degrades ALONE."""

    def test_faulted_tenant_mirror_served_others_on_device(self):
        service, _reg, scheduler = make_world(
            4, breaker_threshold=2, breaker_reset_s=3600.0
        )
        try:
            batch = {
                f"t{i}": random_cost_inputs(20 + i) for i in range(4)
            }
            with injected_faults(seed=7) as faults:
                faults.plan(
                    "tenancy.gather.t2", mode="error", probability=1.0
                )
                for _ in range(4):
                    out = scheduler.cost_all(batch, backend="xla")
                    # the faulted tenant still answers — from the
                    # bit-identical mirror
                    assert_outputs_equal(
                        CK.CostOutputs, out["t2"],
                        CK.cost_numpy(batch["t2"]), "t2",
                    )
                    # healthy tenants keep their device answers
                    for tid in ("t0", "t1", "t3"):
                        assert_outputs_equal(
                            CK.CostOutputs, out[tid],
                            service.cost(batch[tid], backend="xla"),
                            tid,
                        )
            assert scheduler.breakers.is_open("t2")
            assert scheduler.stats.breaker_trips == 1
            assert scheduler.stats.mirror_served >= 3
            # breaker open: later rounds skip the fault point entirely
            # (no probe within the reset window) and keep mirror-serving
            assert not scheduler.breakers.allow("t2")
        finally:
            service.close()

    def test_lockstep_fixed_points_hold_under_one_tenant_chaos(self):
        """Seeded end-to-end chaos: replay the SAME lockstep world with
        and without one tenant at 100% faults. Because the mirror is
        bit-identical, EVERY tenant's trajectory — the faulted one
        included — must match the no-fault run exactly, and the healthy
        tenants must keep riding shared dispatches."""

        def replay(fault_tenant=None):
            service, _reg, scheduler = make_world(
                4, breaker_threshold=2, breaker_reset_s=3600.0
            )
            try:
                replicas = {
                    f"t{i}": np.full(3, 2, np.int32) for i in range(4)
                }
                ctx = (
                    injected_faults(seed=11)
                    if fault_tenant
                    else _null_context()
                )
                with ctx as faults:
                    if fault_tenant:
                        faults.plan(
                            f"tenancy.gather.{fault_tenant}",
                            mode="error", probability=1.0,
                        )
                    for tick in range(6):
                        now = 1000.0 + tick * 10.0
                        batch = {
                            tid: multitenant_fleet_inputs(
                                i, 3, 2, 5, tick, replicas[tid], now
                            )
                            for i, tid in enumerate(sorted(replicas))
                        }
                        decided = scheduler.decide_all(batch)
                        refined = scheduler.cost_all(
                            {
                                tid: multitenant_cost_inputs(
                                    batch[tid], decided[tid].desired
                                )
                                for tid in decided
                            },
                            backend="xla",
                        )
                        for tid in refined:
                            replicas[tid] = np.asarray(
                                refined[tid].desired, np.int32
                            )
                return {
                    tid: r.copy() for tid, r in replicas.items()
                }, scheduler.stats
            finally:
                service.close()

        clean, _clean_stats = replay()
        chaotic, stats = replay(fault_tenant="t1")
        for tid in clean:
            np.testing.assert_array_equal(
                clean[tid], chaotic[tid], err_msg=tid
            )
        assert stats.breaker_trips >= 1
        assert stats.mirror_served >= 1
        # healthy tenants stayed on shared dispatches every tick
        assert stats.cost_dispatches >= 6

    def test_shared_dispatch_failure_isolates_per_tenant(self):
        """A failure of the SHARED dispatch itself (cost.score fault:
        the whole concatenated program dies) falls back to per-tenant
        isolation — every tenant still answers bit-identically via its
        mirror, and nothing raises."""
        service, _reg, scheduler = make_world(3)
        try:
            batch = {
                f"t{i}": random_cost_inputs(60 + i) for i in range(3)
            }
            with injected_faults(seed=3) as faults:
                faults.plan(
                    "cost.score", mode="error", probability=1.0
                )
                out = scheduler.cost_all(batch, backend="xla")
            for tid, inputs in batch.items():
                assert_outputs_equal(
                    CK.CostOutputs, out[tid], CK.cost_numpy(inputs), tid
                )
            assert scheduler.stats.mirror_served == 3
        finally:
            service.close()


    def test_probe_runs_isolated_and_recovery_rejoins_shared(self):
        """An open breaker's probe must NOT re-enter the shared batch
        (a still-poisoned tenant would re-break every healthy tenant's
        round once per window): the probe is an isolated dispatch, and
        only a SUCCESSFUL probe rejoins the tenant to the shared
        concatenation on the following round."""
        clock = {"now": 0.0}
        service, _reg, scheduler = make_world(
            3, breaker_threshold=2, breaker_reset_s=10.0,
            clock=lambda: clock["now"],
        )
        try:
            batch = {
                f"t{i}": random_cost_inputs(80 + i) for i in range(3)
            }
            with injected_faults(seed=5) as faults:
                faults.plan(
                    "tenancy.gather.t1", mode="error", probability=1.0
                )
                scheduler.cost_all(batch, backend="xla")
                scheduler.cost_all(batch, backend="xla")
            assert scheduler.breakers.is_open("t1")
            # fault cleared; probe window elapses
            clock["now"] = 11.0
            shared_before = scheduler.stats.cost_dispatches
            out = scheduler.cost_all(batch, backend="xla")
            # the probe round: t1 answered ISOLATED (correctly), the
            # other two still rode a shared dispatch
            assert scheduler.stats.probes == 1
            assert scheduler.stats.cost_dispatches == shared_before + 1
            assert_outputs_equal(
                CK.CostOutputs, out["t1"],
                service.cost(batch["t1"], backend="xla"), "t1",
            )
            assert not scheduler.breakers.is_open("t1")
            # next round: t1 is back in the shared concatenation
            iso_before = scheduler.stats.isolated_dispatches
            scheduler.cost_all(batch, backend="xla")
            assert scheduler.stats.isolated_dispatches == iso_before
        finally:
            service.close()

    def test_never_an_exception_result_even_when_decide_dies(self):
        """The never-block floor: with the decide seam itself raising
        (shared AND isolated dispatches fail), every tenant still gets
        a REAL DecisionOutputs — hold-current-replicas — never an
        exception object the caller would trip over."""
        from karpenter_tpu.tenancy.scheduler import decide_hold

        def boom(_inputs):
            raise RuntimeError("decider dead")

        service = SolverService(registry=GaugeRegistry(), decider=boom)
        registry = TenantRegistry(
            service=service, registry=GaugeRegistry(),
            specs=[TenantSpec(id="t0"), TenantSpec(id="t1")],
        )
        scheduler = MultiTenantScheduler(registry, service)
        try:
            batch = {
                "t0": random_decide_inputs(0),
                "t1": random_decide_inputs(1),
            }
            out = scheduler.decide_all(batch)
            for tid, inputs in batch.items():
                assert_outputs_equal(
                    D.DecisionOutputs, out[tid], decide_hold(inputs),
                    tid,
                )
            assert scheduler.stats.tenant_failures >= 2
        finally:
            service.close()


class _null_context:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


class TestFairness:
    def test_small_fleet_rides_one_round(self):
        admission = WeightedAdmission(budget_rows=100)
        schedule = admission.rounds(
            {"a": 10, "b": 20, "c": 30}, {"a": 1, "b": 1, "c": 1}
        )
        assert len(schedule) == 1
        assert sorted(schedule[0]) == ["a", "b", "c"]

    def test_noisy_tenant_cannot_starve_the_queue(self):
        """A tenant demanding 10x the budget dispatches ALONE; the
        small tenants ride their own round rather than waiting behind
        it forever."""
        admission = WeightedAdmission(budget_rows=64)
        schedule = admission.rounds(
            {"noisy": 640, "a": 8, "b": 8},
            {"noisy": 1, "a": 1, "b": 1},
        )
        assert len(schedule) == 2
        flat = [t for r in schedule for t in r]
        assert sorted(flat) == ["a", "b", "noisy"]
        lone = [r for r in schedule if r == ["noisy"]]
        assert lone, f"noisy tenant should dispatch alone: {schedule}"

    def test_weighted_shares_converge(self):
        """Over many rounds, admitted-first counts track weights: the
        weight-3 tenant reaches the head of the schedule about three
        times as often as the weight-1 tenant."""
        admission = WeightedAdmission(budget_rows=32)
        first = {"heavy": 0, "light": 0}
        for _ in range(60):
            # both want more than one budget together: one defers
            schedule = admission.rounds(
                {"heavy": 24, "light": 24},
                {"heavy": 3.0, "light": 1.0},
            )
            first[schedule[0][0]] += 1
        assert first["heavy"] > first["light"] * 2, first

    def test_every_round_admits_at_least_one(self):
        admission = WeightedAdmission(budget_rows=4)
        schedule = admission.rounds(
            {"big1": 100, "big2": 100}, {"big1": 1, "big2": 1}
        )
        assert len(schedule) == 2
        assert all(len(r) == 1 for r in schedule)


class TestTenantRegistry:
    def test_namespaced_stacks_are_independent(self):
        service = SolverService(registry=GaugeRegistry())
        try:
            registry = TenantRegistry(
                service=service, registry=GaugeRegistry(),
                specs=[TenantSpec(id="a"), TenantSpec(id="b")],
            )
            a, b = registry.get("a"), registry.get("b")
            assert a.store is not b.store
            assert a.forecaster is not b.forecaster
            assert a.cost_engine is not b.cost_engine
            # per-tenant history is namespaced: feeding a's forecaster
            # leaves b's empty
            a.forecaster.history.append(("q", "x"), 1.0, 5.0)
            assert b.forecaster.history.count(("q", "x")) == 0
        finally:
            service.close()

    def test_remove_retires_tenant_gauge_series(self):
        service = SolverService(registry=GaugeRegistry())
        metrics_registry = GaugeRegistry()
        try:
            registry = TenantRegistry(
                service=service, registry=metrics_registry,
                specs=[TenantSpec(id="a"), TenantSpec(id="b")],
            )
            scheduler = MultiTenantScheduler(registry, service)
            batch = {
                "a": random_cost_inputs(1),
                "b": random_cost_inputs(2),
            }
            scheduler.cost_all(batch, backend="xla")
            text = metrics_registry.expose_text()
            assert 'karpenter_tenant_backlog_rows{name="a"' in text
            registry.remove("a")
            text = metrics_registry.expose_text()
            assert 'name="a"' not in text, (
                "deleted tenant's series must retire"
            )
            assert 'karpenter_tenant_backlog_rows{name="b"' in text
            # breaker + admission credit forgotten too
            assert not scheduler.breakers.is_open("a")
            assert registry.metrics.active.get("-", "-") == 1.0
        finally:
            service.close()

    def test_per_tenant_fencing_is_independent(self, tmp_path):
        """Two tenants' recovery state lives in disjoint journal dirs:
        re-claiming tenant a's fence bumps a's generation only."""
        from karpenter_tpu.recovery.fence import read_generation

        service = SolverService(registry=GaugeRegistry())
        try:
            registry = TenantRegistry(
                service=service, registry=GaugeRegistry(),
                journal_dir=str(tmp_path),
                specs=[TenantSpec(id="a"), TenantSpec(id="b")],
            )
            dir_a = registry.journal_dir_for("a")
            dir_b = registry.journal_dir_for("b")
            assert dir_a != dir_b and os.path.isdir(dir_a)
            rec_a = registry.get("a").recovery()
            rec_b = registry.get("b").recovery()
            assert rec_a is not None and rec_b is not None
            gen_b = rec_b.fence.generation
            rec_a.close()
            registry.get("a")._recovery = None
            rec_a2 = registry.get("a").recovery()  # a "restart" of a
            assert rec_a2.fence.generation > 1
            # b's durable generation is untouched by a's restart
            assert read_generation(dir_b) == gen_b
        finally:
            registry.close()
            service.close()

    def test_load_tenant_config_shapes_and_errors(self, tmp_path):
        path = tmp_path / "tenants.json"
        path.write_text(json.dumps({
            "tenants": [
                {"id": "prod", "weight": 3.0},
                {"id": "dev", "pricingFile": "x.json"},
            ]
        }))
        specs = load_tenant_config(str(path))
        assert [s.id for s in specs] == ["prod", "dev"]
        assert specs[0].weight == 3.0
        assert specs[1].pricing_file == "x.json"
        path.write_text(json.dumps([{"id": "a"}, {"id": "a"}]))
        with pytest.raises(ValueError, match="duplicate"):
            load_tenant_config(str(path))
        path.write_text(json.dumps([{"id": "../evil"}]))
        with pytest.raises(ValueError, match="path-safe"):
            load_tenant_config(str(path))
        path.write_text(json.dumps([{"id": "a", "weight": 0}]))
        with pytest.raises(ValueError, match="weight"):
            load_tenant_config(str(path))

    def test_tenant_gauges_pass_exposition_lint(self):
        service = SolverService(registry=GaugeRegistry())
        metrics_registry = GaugeRegistry()
        try:
            registry = TenantRegistry(
                service=service, registry=metrics_registry,
                specs=[TenantSpec(id="t0"), TenantSpec(id="t1")],
            )
            scheduler = MultiTenantScheduler(registry, service)
            with injected_faults(seed=1) as faults:
                faults.plan(
                    "tenancy.gather.t1", mode="error", probability=1.0
                )
                for _ in range(4):
                    scheduler.cost_all(
                        {
                            "t0": random_cost_inputs(0),
                            "t1": random_cost_inputs(1),
                        },
                        backend="xla",
                    )
            typed, series = _lint_exposition(
                metrics_registry.expose_text()
            )
            for family in (
                "karpenter_tenant_active",
                "karpenter_tenant_weight",
                "karpenter_tenant_degraded",
                "karpenter_tenant_backlog_rows",
                "karpenter_tenant_admission_rounds",
                "karpenter_tenant_decisions_total",
                "karpenter_tenant_dispatches_total",
                "karpenter_tenant_mirror_served_total",
                "karpenter_tenant_fallback_served_total",
                "karpenter_tenant_breaker_trips_total",
                "karpenter_tenant_deferrals_total",
            ):
                assert family in typed, family
            assert typed["karpenter_tenant_breaker_trips_total"] == (
                "counter"
            )
        finally:
            service.close()


class TestBreakerBoard:
    def test_trip_probe_recover(self):
        clock = {"now": 0.0}
        board = TenantBreakerBoard(
            threshold=2, reset_s=10.0, clock=lambda: clock["now"]
        )
        assert board.allow("t")
        assert not board.record_failure("t")
        assert board.record_failure("t")  # trips
        assert board.is_open("t")
        assert not board.allow("t")  # inside the open window
        clock["now"] = 11.0
        assert board.allow("t")  # the probe
        assert not board.allow("t")  # next probe already scheduled
        assert board.record_success("t")  # probe success closes
        assert not board.is_open("t")
        assert board.allow("t")


class TestPricingFeed:
    def test_file_source_reads_and_reloads_on_mtime(self, tmp_path):
        from karpenter_tpu.cost import CostModel, FilePricingSource

        path = tmp_path / "prices.json"
        path.write_text(json.dumps({"m5.large": 0.5}))
        source = FilePricingSource(str(path))
        model = CostModel(pricing=source)
        assert model.on_demand("m5.large") == 0.5
        # catalog fallback for types the feed doesn't carry
        assert model.on_demand("g5.xlarge") == pytest.approx(1.006)
        path.write_text(
            json.dumps(
                {"catalog": {"m5.large": 0.75}, "spotMultiplier": 0.2}
            )
        )
        os.utime(path, (time.time() + 5, time.time() + 5))
        source._next_check = 0.0  # skip the 1s mtime-poll throttle
        assert model.on_demand("m5.large") == 0.75
        assert model.effective_spot_multiplier() == 0.2

    def test_broken_reload_keeps_last_good_catalog(self, tmp_path):
        from karpenter_tpu.cost import FilePricingSource

        path = tmp_path / "prices.json"
        path.write_text(json.dumps({"m5.large": 0.5}))
        source = FilePricingSource(str(path))
        assert source.price("m5.large") == 0.5
        path.write_text("{not json at all")
        os.utime(path, (time.time() + 5, time.time() + 5))
        source._next_check = 0.0  # skip the 1s mtime-poll throttle
        assert source.price("m5.large") == 0.5  # never-block
        path.unlink()
        source._next_check = 0.0
        assert source.price("m5.large") == 0.5  # vanished file too

    def test_first_load_fails_loudly(self, tmp_path):
        from karpenter_tpu.cost import FilePricingSource

        with pytest.raises(ValueError):
            FilePricingSource(str(tmp_path / "missing.json"))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"m5.large": -1}))
        with pytest.raises(ValueError, match="negative"):
            FilePricingSource(str(bad))

    def test_per_tenant_pricing_via_registry(self, tmp_path):
        cheap = tmp_path / "cheap.json"
        cheap.write_text(json.dumps({"m5.large": 0.01}))
        dear = tmp_path / "dear.json"
        dear.write_text(json.dumps({"m5.large": 9.99}))
        service = SolverService(registry=GaugeRegistry())
        try:
            registry = TenantRegistry(
                service=service,
                specs=[
                    TenantSpec(id="a", pricing_file=str(cheap)),
                    TenantSpec(id="b", pricing_file=str(dear)),
                ],
            )
            assert registry.get("a").cost_model.on_demand(
                "m5.large"
            ) == 0.01
            assert registry.get("b").cost_model.on_demand(
                "m5.large"
            ) == 9.99
        finally:
            service.close()


class TestPerMetricSLO:
    def test_target_for_fallback_chain(self):
        from karpenter_tpu.api.horizontalautoscaler import (
            SLOMetricTarget,
            SLOSpec,
        )

        slo = SLOSpec(
            target_value=4.0,
            metrics=[
                SLOMetricTarget(target_value=10.0),
                SLOMetricTarget(),  # falls back to the spec-wide value
            ],
        )
        assert slo.target_for(0) == 10.0
        assert slo.target_for(1) == 4.0
        assert slo.target_for(2) == 4.0  # beyond the list
        assert SLOSpec().target_for(0) is None

    def test_validation_rejects_nonpositive_per_metric_target(self):
        from karpenter_tpu.api.horizontalautoscaler import (
            SLOMetricTarget,
            SLOSpec,
        )

        with pytest.raises(ValueError):
            SLOSpec(
                metrics=[SLOMetricTarget(target_value=0.0)]
            ).validate()

    def test_per_metric_targets_serialize_round_trip(self):
        from karpenter_tpu.api.horizontalautoscaler import (
            SLOMetricTarget,
            SLOSpec,
        )
        from karpenter_tpu.api.serialization import from_dict, to_dict

        slo = SLOSpec(
            target_value=4.0,
            violation_cost_weight=10.0,
            metrics=[SLOMetricTarget(target_value=7.5)],
        )
        doc = to_dict(slo)
        assert doc["metrics"][0]["targetValue"] == 7.5
        back = from_dict(SLOSpec, doc)
        assert back.metrics[0].target_value == 7.5

    def test_worst_case_risk_across_per_metric_targets(self):
        """A tight per-metric target on metric 1 must dominate the risk
        even when metric 0's shared target is comfortable — the kernel
        maxes over metrics, the engine feeds per-metric capacities."""
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.horizontalautoscaler import (
            Behavior,
            CrossVersionObjectReference,
            HorizontalAutoscaler,
            HorizontalAutoscalerSpec,
            MetricTarget,
            SLOMetricTarget,
            SLOSpec,
        )
        from karpenter_tpu.cost import CostEngine

        class Row:
            def __init__(self, ha, observed):
                self.ha = ha
                self.observed = observed
                self.values = [value for (_s, _t, value) in observed]
                self.custom = False

        def build_engine_and_rows(per_metric):
            ha = HorizontalAutoscaler(
                metadata=ObjectMeta(name="ha", namespace="default"),
                spec=HorizontalAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="ScalableNodeGroup", name="g"
                    ),
                    min_replicas=1,
                    max_replicas=100,
                    behavior=Behavior(
                        slo=SLOSpec(
                            target_value=100.0,
                            violation_cost_weight=1000.0,
                            metrics=per_metric,
                        )
                    ),
                ),
            )
            target = MetricTarget(type="AverageValue", value=100.0)
            rows = [Row(ha, [(None, target, 80.0), (None, target, 80.0)])]
            return CostEngine(cost_fn=CK.cost_numpy), rows

        base = D.DecisionOutputs(
            desired=np.asarray([2], np.int32),
            recommendation=np.asarray([2], np.int32),
            limited=np.asarray([2], np.int32),
            able_to_scale=np.asarray([True]),
            scaling_unbounded=np.asarray([True]),
            able_at=np.asarray([0.0], np.float32),
            rate_limited=np.asarray([False]),
            up_ceiling=np.asarray([100], np.int32),
            down_floor=np.asarray([1], np.int32),
        )
        # shared 100-per-replica target: 2 replicas absorb the demand
        engine, rows = build_engine_and_rows(None)
        relaxed = engine.adjust(rows, base)
        # metric 1 tightened to 10-per-replica: worst-case risk forces
        # replicas up
        engine, rows = build_engine_and_rows(
            [SLOMetricTarget(), SLOMetricTarget(target_value=10.0)]
        )
        tight = engine.adjust(rows, base)
        assert int(tight.desired[0]) > int(relaxed.desired[0])


class TestForecastGaugeRetirement:
    def test_dropping_forecast_spec_retires_series(self):
        """The frozen-series audit (docs/multitenancy.md satellite): an
        HA that DROPS spec.behavior.forecast must lose its
        karpenter_forecast_* series on the next pass, not pin the last
        pre-opt-out skill forever."""
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.horizontalautoscaler import (
            Behavior,
            ForecastSpec,
            HorizontalAutoscaler,
            HorizontalAutoscalerSpec,
            MetricTarget,
        )
        from karpenter_tpu.forecast import FleetForecaster

        registry = GaugeRegistry()
        clock = {"now": 1000.0}
        forecaster = FleetForecaster(
            forecast_fn=FM.forecast_numpy,
            registry=registry,
            clock=lambda: clock["now"],
        )
        ha = HorizontalAutoscaler(
            metadata=ObjectMeta(name="ha", namespace="default"),
            spec=HorizontalAutoscalerSpec(
                behavior=Behavior(
                    forecast=ForecastSpec(min_samples=2)
                )
            ),
        )

        class Row:
            def __init__(self, ha, value):
                self.ha = ha
                self.observed = [
                    (
                        None,
                        MetricTarget(type="AverageValue", value=4.0),
                        value,
                    )
                ]
                self.custom = False
                self.stale_metrics = set()

        for i in range(6):
            clock["now"] += 10.0
            forecaster.forecast_rows(
                [Row(ha, 10.0 + i)], clock["now"]
            )
        assert (
            registry.gauge("forecast", "skill").get("ha", "default")
            is not None
        )
        # the HA drops its forecast spec: next pass retires the series
        ha.spec.behavior.forecast = None
        clock["now"] += 10.0
        forecaster.forecast_rows([Row(ha, 20.0)], clock["now"])
        assert (
            registry.gauge("forecast", "skill").get("ha", "default")
            is None
        )
        assert (
            registry.gauge("forecast", "horizon_value").get(
                "ha", "default"
            )
            is None
        )


class TestProducerGaugeRetirement:
    def test_deleted_producer_retires_queue_series(self):
        """The other frozen-series find of the audit: a deleted
        MetricsProducer's queue/capacity gauges must leave /metrics."""
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.metricsproducer import MetricsProducer
        from karpenter_tpu.controllers.metricsproducer import (
            MetricsProducerController,
        )

        class Factory:
            def __init__(self, registry):
                self.registry = registry

        registry = GaugeRegistry()
        registry.register("queue", "length").set("mq", "default", 41.0)
        registry.register("pending_capacity", "pending_pods").set(
            "mq", "default", 7.0
        )
        controller = MetricsProducerController(Factory(registry))
        mp = MetricsProducer(
            metadata=ObjectMeta(name="mq", namespace="default")
        )
        controller.on_deleted(mp)
        assert registry.gauge("queue", "length").get(
            "mq", "default"
        ) is None
        assert registry.gauge("pending_capacity", "pending_pods").get(
            "mq", "default"
        ) is None

    def test_deleted_producer_retires_reserved_capacity_matrix(self):
        """reserved_capacity names are {resource}_{metric_type} — the
        retirement hook must cover the whole matrix subsystem-wide, not
        a hand-enumerated name list."""
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.metricsproducer import MetricsProducer
        from karpenter_tpu.controllers.metricsproducer import (
            MetricsProducerController,
        )
        from karpenter_tpu.metrics.producers import reservedcapacity as RC

        class Factory:
            def __init__(self, registry):
                self.registry = registry

        registry = GaugeRegistry()
        RC.register_gauges(registry)
        registry.gauge("reserved_capacity", "cpu_utilization").set(
            "rc", "default", 0.8
        )
        registry.gauge("reserved_capacity", "memory_capacity").set(
            "rc", "default", 64.0
        )
        MetricsProducerController(Factory(registry)).on_deleted(
            MetricsProducer(
                metadata=ObjectMeta(name="rc", namespace="default")
            )
        )
        assert registry.gauge("reserved_capacity", "cpu_utilization").get(
            "rc", "default"
        ) is None
        assert registry.gauge("reserved_capacity", "memory_capacity").get(
            "rc", "default"
        ) is None


class TestSimulateMultitenant:
    def test_deterministic_and_amortizing(self):
        a = simulate_multitenant(tenants=6, ticks=6, rows=3, seed=0)
        b = simulate_multitenant(tenants=6, ticks=6, rows=3, seed=0)
        assert a == b, "seeded replay must be deterministic"
        assert a["tenants"] == 6
        assert a["decisions"] == 6 * 6 * 3
        # the whole point: far fewer dispatches than the sequential
        # per-tenant loop would pay
        assert a["dispatch_amortization"] >= 3.0
        assert a["mirror_served"] == 0
        assert set(a["aggregate_replicas"]) == {
            "tick_0", "tick_3", "tick_5"
        }

    def test_tenant_config_drives_the_replay(self, tmp_path):
        path = tmp_path / "tenants.json"
        path.write_text(json.dumps([
            {"id": "alpha", "weight": 2.0}, {"id": "beta"},
        ]))
        report = simulate_multitenant(
            ticks=3, rows=2, tenant_config=str(path)
        )
        assert report["tenants"] == 2


class TestSidecarTenantMetadata:
    def test_tenant_id_rides_grpc_metadata(self):
        grpc = pytest.importorskip("grpc")  # noqa: F841
        from karpenter_tpu.metrics.registry import GaugeRegistry as GR
        from karpenter_tpu.sidecar.client import SolverClient
        from karpenter_tpu.sidecar.server import SolverServer

        registry = GR()
        server = SolverServer(port=0, host="127.0.0.1", registry=registry)
        port = server.start()
        client = SolverClient(f"127.0.0.1:{port}", tenant="acme")
        try:
            ok, _meta = client.health()
            assert ok
            assert registry.gauge("tenant", "rpcs_total").get(
                "acme", "-"
            ) == 1.0
        finally:
            client.close()
            server.stop()

    def test_no_tenant_is_wire_compatible(self):
        grpc = pytest.importorskip("grpc")  # noqa: F841
        from karpenter_tpu.metrics.registry import GaugeRegistry as GR
        from karpenter_tpu.sidecar.client import SolverClient
        from karpenter_tpu.sidecar.server import SolverServer

        registry = GR()
        server = SolverServer(port=0, host="127.0.0.1", registry=registry)
        port = server.start()
        client = SolverClient(f"127.0.0.1:{port}")
        try:
            ok, _meta = client.health()
            assert ok
            assert not registry.gauge("tenant", "rpcs_total").samples()
        finally:
            client.close()
            server.stop()


class TestRegressionGuard:
    def test_batched_multitenant_beats_sequential_loop(self):
        """Non-slow guard for the bench-multitenant claim: one
        concatenated decide+cost tick over 64 tenants must beat 64
        per-tenant dispatch pairs (generously — the published 1k-tenant
        numbers live in docs/BENCHMARKS.md)."""
        service, _reg, scheduler = make_world(
            64, max_rows_per_round=64 * 4
        )
        try:
            decide_batch = {
                f"t{i}": multitenant_fleet_inputs(
                    i, 4, 2, 0, 3, np.full(4, 2, np.int32), 1000.0
                )
                for i in range(64)
            }
            cost_batch = {
                tid: multitenant_cost_inputs(
                    decide_batch[tid], np.full(4, 5, np.int32)
                )
                for tid in decide_batch
            }

            def batched():
                scheduler.decide_all(decide_batch)
                scheduler.cost_all(cost_batch, backend="xla")

            def sequential():
                for tid in decide_batch:
                    service.decide(decide_batch[tid])
                    service.cost(cost_batch[tid], backend="xla")

            batched()  # warm both program shapes
            sequential()

            def best_of(fn, reps=3):
                times = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    fn()
                    times.append(time.perf_counter() - t0)
                return min(times)

            t_batched = best_of(batched)
            t_sequential = best_of(sequential)
            assert t_batched < t_sequential, (
                f"batched {t_batched * 1e3:.2f}ms not faster than "
                f"sequential {t_sequential * 1e3:.2f}ms"
            )
        finally:
            service.close()


class TestTenantWeightedDeadlines:
    """PR 11 named follow-up, closed in PR 13: fairness bounds rows per
    round, not per-tenant latency — deadline_s bounds the latter, with
    each tenant's budget scaled by weight / mean weight. An exhausted
    budget serves the tenant immediately from the bit-identical mirror
    and counts a DEFERRAL (karpenter_tenant_deferrals_total), never a
    breaker failure."""

    def test_light_tenant_escapes_heavy_waits(self):
        # one tenant's rows fill a round, so the schedule is 3 rounds;
        # the ticking clock makes every round cost 'wall time', and the
        # lightweight tenants' small budgets expire mid-schedule
        ticks = {"now": 0.0}

        def clock():
            ticks["now"] += 1.0
            return ticks["now"]

        service, registry, scheduler = make_world(
            n_tenants=3, weights=[10.0, 0.1, 0.1],
            max_rows_per_round=6, deadline_s=10.0, clock=clock,
        )
        try:
            # budgets: mean weight 3.4 -> heavy ~29.4s (never expires
            # under the ticking clock), lights ~0.29s (expire by the
            # first deferred round)
            batch = {
                f"t{i}": random_cost_inputs(seed=40 + i, n=6)
                for i in range(3)
            }
            out = scheduler.cost_all(batch, backend="numpy")
            assert scheduler.stats.deadline_escapes >= 1
            assert scheduler.stats.deferrals >= (
                scheduler.stats.deadline_escapes
            )
            # no breaker charge: backlog is the plane's condition, not
            # the tenant's fault
            assert scheduler.stats.tenant_failures == 0
            for tid in batch:
                assert not scheduler.breakers.is_open(tid)
            # the escaped tenants' answers are the bit-identical mirror
            for tid, inputs in batch.items():
                assert_outputs_equal(
                    CK.CostOutputs, out[tid], CK.cost_numpy(inputs),
                    context=tid,
                )
        finally:
            service.close()

    def test_no_deadline_means_no_escapes(self):
        ticks = {"now": 0.0}

        def clock():
            ticks["now"] += 1.0
            return ticks["now"]

        service, registry, scheduler = make_world(
            n_tenants=3, weights=[10.0, 0.1, 0.1],
            max_rows_per_round=6, clock=clock,
        )
        try:
            batch = {
                f"t{i}": random_cost_inputs(seed=50 + i, n=6)
                for i in range(3)
            }
            out = scheduler.cost_all(batch, backend="numpy")
            assert scheduler.stats.deadline_escapes == 0
            for tid, inputs in batch.items():
                assert_outputs_equal(
                    CK.CostOutputs, out[tid], CK.cost_numpy(inputs),
                    context=tid,
                )
        finally:
            service.close()

    def test_solve_all_weighted_timeouts(self):
        """The bin-pack face: each tenant's queue deadline is its
        weighted budget — an expiry serves binpack_numpy and counts a
        deferral, not a breaker failure."""
        import time as _time

        from karpenter_tpu.ops.binpack import BinPackInputs
        from karpenter_tpu.ops.numpy_binpack import binpack_numpy

        def make_binpack_inputs(seed):
            rng = np.random.RandomState(seed)
            return BinPackInputs(
                pod_requests=rng.uniform(0.1, 2.0, (8, 2)).astype(
                    np.float32
                ),
                pod_valid=np.ones(8, bool),
                pod_intolerant=np.zeros((8, 1), bool),
                pod_required=np.zeros((8, 1), bool),
                group_allocatable=rng.uniform(4.0, 16.0, (3, 2)).astype(
                    np.float32
                ),
                group_taints=np.zeros((3, 1), bool),
                group_labels=np.zeros((3, 1), bool),
            )

        def slow_solver(inputs, buckets=32, backend=None):
            _time.sleep(0.25)
            return binpack_numpy(inputs, buckets=buckets)

        service = SolverService(
            registry=GaugeRegistry(), device_solver=slow_solver,
        )
        metrics_registry = GaugeRegistry()
        registry = TenantRegistry(
            service=service, registry=metrics_registry,
            specs=[
                TenantSpec(id="heavy", weight=1.999),
                TenantSpec(id="light", weight=0.001),
            ],
        )
        scheduler = MultiTenantScheduler(
            registry, service, deadline_s=10.0
        )
        try:
            batch = {
                "heavy": make_binpack_inputs(seed=3),
                "light": make_binpack_inputs(seed=4),
            }
            out = scheduler.solve_all(batch, buckets=8)
            # light's budget (10s x 0.001 / 1.0 = 10ms) expires inside
            # the 250ms dispatch; heavy's (~20s) does not
            assert scheduler.stats.deadline_escapes >= 1
            assert scheduler.stats.tenant_failures == 0
            assert not scheduler.breakers.is_open("light")
            for tid, inputs in batch.items():
                ref = binpack_numpy(inputs, buckets=8)
                np.testing.assert_array_equal(
                    np.asarray(out[tid].assigned),
                    np.asarray(ref.assigned), err_msg=tid,
                )
        finally:
            service.close()

    def test_budgets_scale_with_weight(self):
        service, registry, scheduler = make_world(
            n_tenants=2, weights=[3.0, 1.0], deadline_s=8.0,
        )
        try:
            budgets = scheduler._deadline_budgets(
                ["t0", "t1"], registry.weights()
            )
            # mean weight 2.0: t0 = 8 * 3/2 = 12s, t1 = 8 * 1/2 = 4s
            assert budgets["t0"] == pytest.approx(12.0)
            assert budgets["t1"] == pytest.approx(4.0)
        finally:
            service.close()


class TestEventPassAdmission:
    """ISSUE 14 tenancy pin: event passes are ordinary solver traffic.
    A tenant runtime whose fleet-decide seam routes through the shared
    MultiTenantScheduler keeps riding WeightedAdmission when the decide
    is triggered by a coalesced EVENT PASS (engine event-driven mode)
    instead of a tick — sub-second reaction must not become a fairness
    bypass."""

    def test_event_pass_decides_ride_weighted_admission(self):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        from test_chaos import queue_ha, sng_of

        service, _tenants, scheduler = make_world(2)
        runtimes = []
        try:
            for tid in ("t0", "t1"):
                clock = {"now": 1000.0}
                provider = FakeFactory()
                provider.node_replicas["g"] = 5
                runtime = KarpenterRuntime(
                    Options(
                        event_driven=True,
                        event_debounce_s=0.01,
                        event_thread=False,
                    ),
                    cloud_provider_factory=provider,
                    clock=(lambda c=clock: c["now"]),
                )
                # the tenant's decide seam: through the SHARED scheduler
                # (concat + WeightedAdmission + per-tenant isolation),
                # exactly how a live multi-tenant deployment fronts the
                # one solver service
                runtime.batch_autoscaler.decider = (
                    lambda inputs, t=tid:
                    scheduler.decide_all({t: inputs})[t]
                )
                runtime.registry.register("queue", "length").set(
                    "q", "default", 41.0
                )
                runtime.store.create(sng_of("g", replicas=5))
                runtime.store.create(
                    queue_ha("g", 'karpenter_queue_length{name="q"}')
                )
                runtimes.append((runtime, provider, clock))

            rounds_before = scheduler.stats.admission_rounds
            decides_before = scheduler.stats.decide_calls
            for runtime, provider, clock in runtimes:
                # NO ticks: the create events alone must cascade the
                # decide -> scale patch -> actuation through passes
                for _ in range(6):
                    if runtime.manager.dirty_count() == 0:
                        break
                    clock["now"] += 0.01
                    runtime.manager.run_event_pass()
                assert provider.node_replicas["g"] == 11, (
                    "event passes must actuate the fleet decide "
                    "(queue 41 / target 4 -> 11)"
                )
            assert scheduler.stats.decide_calls - decides_before >= 2, (
                "each tenant's event-pass decide must flow through the "
                "shared scheduler"
            )
            assert scheduler.stats.admission_rounds - rounds_before >= 2, (
                "event-pass dispatches must take WeightedAdmission "
                "rounds, not bypass fairness"
            )
        finally:
            for runtime, _provider, _clock in runtimes:
                runtime.close()
            service.close()
