"""Pallas fused bin-pack kernel == XLA reference path, element for element.

The Pallas kernel (ops/pallas_binpack.py) runs compiled Mosaic on TPU; on
the CPU test mesh it runs in interpreter mode, which executes the same
kernel logic (tiling, grid accumulation, padding) without the TPU compiler.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from karpenter_tpu.ops import binpack as B
from karpenter_tpu.ops import pallas_binpack as PB

from test_binpack import make_inputs


def random_inputs(rng, pods, types, taints=8, labels=8, n_resources=3):
    req = rng.uniform(0.05, 8.0, (pods, n_resources)).astype(np.float32)
    alloc = rng.uniform(1.0, 64.0, (types, n_resources)).astype(np.float32)
    # a few empty groups exercise the zero-allocatable rule
    empty = rng.random(types) < 0.1
    alloc[empty] = 0.0
    return B.BinPackInputs(
        pod_requests=jnp.asarray(req),
        pod_valid=jnp.asarray(rng.random(pods) > 0.05),
        pod_intolerant=jnp.asarray(rng.random((pods, taints)) < 0.1),
        pod_required=jnp.asarray(rng.random((pods, labels)) < 0.05),
        group_allocatable=jnp.asarray(alloc),
        group_taints=jnp.asarray(rng.random((types, taints)) < 0.15),
        group_labels=jnp.asarray(rng.random((types, labels)) < 0.8),
    )


def assert_outputs_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.assigned), np.asarray(b.assigned))
    np.testing.assert_array_equal(
        np.asarray(a.assigned_count), np.asarray(b.assigned_count)
    )
    np.testing.assert_array_equal(
        np.asarray(a.nodes_needed), np.asarray(b.nodes_needed)
    )
    np.testing.assert_array_equal(
        np.asarray(a.lp_bound), np.asarray(b.lp_bound)
    )
    assert int(a.unschedulable) == int(b.unschedulable)


class TestPallasMatchesXLA:
    @pytest.mark.parametrize("seed", range(5))
    def test_random_parity(self, seed):
        rng = np.random.default_rng(seed)
        inputs = random_inputs(rng, pods=203, types=37)
        xla = B.binpack(inputs, buckets=16)
        pallas = PB.binpack_pallas(
            inputs, buckets=16, tile_p=64, interpret=True
        )
        assert_outputs_equal(xla, pallas)

    def test_padding_invisible(self):
        """P not a multiple of tile_p, T/K/L far from the 128 lane."""
        rng = np.random.default_rng(99)
        inputs = random_inputs(rng, pods=65, types=5, taints=3, labels=2)
        xla = B.binpack(inputs, buckets=8)
        pallas = PB.binpack_pallas(inputs, buckets=8, tile_p=64, interpret=True)
        assert_outputs_equal(xla, pallas)

    @pytest.mark.parametrize("seed", range(3))
    def test_score_parity(self, seed):
        """pod_group_score (preferred node affinity) steers assignment
        identically in both backends: max score among feasible, lowest
        index tie-break."""
        import dataclasses

        rng = np.random.default_rng(200 + seed)
        inputs = dataclasses.replace(
            random_inputs(rng, pods=203, types=37),
            pod_group_score=jnp.asarray(
                rng.integers(0, 100, (203, 37)).astype(np.float32)
            ),
            pod_weight=jnp.asarray(
                rng.integers(1, 2000, 203).astype(np.int32)
            ),
        )
        xla = B.binpack(inputs, buckets=16)
        pallas = PB.binpack_pallas(
            inputs, buckets=16, tile_p=64, interpret=True
        )
        assert_outputs_equal(xla, pallas)
        # scoring changed the assignment vs first-feasible
        free = B.binpack(
            dataclasses.replace(inputs, pod_group_score=None), buckets=16
        )
        assert not np.array_equal(
            np.asarray(free.assigned), np.asarray(xla.assigned)
        )
        # and never assigned an infeasible group: pod counts conserved
        # over the VALID rows (invalid rows never enter any aggregate)
        total = int(np.sum(np.asarray(xla.assigned_count))) + int(
            xla.unschedulable
        )
        valid = np.asarray(inputs.pod_valid)
        assert total == int(np.sum(np.asarray(inputs.pod_weight)[valid]))

    def test_score_tiebreak_is_lowest_index(self):
        """Uniform scores must reproduce first-feasible exactly."""
        import dataclasses

        rng = np.random.default_rng(42)
        base = random_inputs(rng, pods=90, types=11)
        uniform = dataclasses.replace(
            base,
            pod_group_score=jnp.full((90, 11), 7.0, jnp.float32),
        )
        assert_outputs_equal(
            B.binpack(base, buckets=8), B.binpack(uniform, buckets=8)
        )
        assert_outputs_equal(
            B.binpack(base, buckets=8),
            PB.binpack_pallas(uniform, buckets=8, tile_p=64, interpret=True),
        )

    @pytest.mark.parametrize("seed", range(3))
    def test_forbidden_parity(self, seed):
        """pod_group_forbidden (required node affinity, host-evaluated)
        masks feasibility identically in both backends, weighted rows
        included, and the constraint is actually enforced."""
        import dataclasses

        rng = np.random.default_rng(100 + seed)
        base = random_inputs(rng, pods=203, types=37)
        inputs = dataclasses.replace(
            base,
            pod_group_forbidden=jnp.asarray(rng.random((203, 37)) < 0.4),
            pod_weight=jnp.asarray(
                rng.integers(1, 2000, 203).astype(np.int32)
            ),
        )
        xla = B.binpack(inputs, buckets=16)
        pallas = PB.binpack_pallas(
            inputs, buckets=16, tile_p=64, interpret=True
        )
        assert_outputs_equal(xla, pallas)
        assigned = np.asarray(xla.assigned)
        forbidden = np.asarray(inputs.pod_group_forbidden)
        rows = np.arange(len(assigned))[assigned >= 0]
        assert not forbidden[rows, assigned[assigned >= 0]].any()
        # and the mask changes the outcome vs the unconstrained solve
        free = B.binpack(
            dataclasses.replace(inputs, pod_group_forbidden=None), buckets=16
        )
        assert not np.array_equal(np.asarray(free.assigned), assigned)

    @pytest.mark.parametrize("seed", range(3))
    def test_exclusive_parity(self, seed):
        """pod_exclusive (hostname self-anti-affinity: a pod takes a
        whole node) forces bucket=B identically in both backends, and a
        group's node count always covers its exclusive weight."""
        import dataclasses

        rng = np.random.default_rng(300 + seed)
        inputs = dataclasses.replace(
            random_inputs(rng, pods=203, types=37),
            pod_exclusive=jnp.asarray(rng.random(203) < 0.3),
            pod_weight=jnp.asarray(rng.integers(1, 40, 203).astype(np.int32)),
        )
        xla = B.binpack(inputs, buckets=16)
        pallas = PB.binpack_pallas(
            inputs, buckets=16, tile_p=64, interpret=True
        )
        assert_outputs_equal(xla, pallas)
        assigned = np.asarray(xla.assigned)
        excl = np.asarray(inputs.pod_exclusive)
        w = np.asarray(inputs.pod_weight)
        for t in range(37):
            assert int(xla.nodes_needed[t]) >= int(
                w[(assigned == t) & excl].sum()
            )
        # the flag changes packing (same assignment, more nodes) on at
        # least one group vs the unconstrained solve
        free = B.binpack(
            dataclasses.replace(inputs, pod_exclusive=None), buckets=16
        )
        np.testing.assert_array_equal(
            np.asarray(free.assigned), assigned
        )  # feasibility/assignment untouched
        assert (
            np.asarray(xla.nodes_needed) >= np.asarray(free.nodes_needed)
        ).all()
        # and the flag is not a silent no-op: 30% exclusive of 203
        # weighted rows must strictly raise some group's node count
        assert (
            np.asarray(xla.nodes_needed) > np.asarray(free.nodes_needed)
        ).any()

    def test_semantics_taints_and_labels(self):
        # group 0 tainted (pod 0 intolerant); group 1 lacks pod 1's label
        inputs = make_inputs(
            pod_requests=[[1, 1], [1, 1]],
            group_allocatable=[[4, 4], [4, 4]],
            pod_intolerant=[[True, False], [False, False]],
            group_taints=[[True, False], [False, False]],
            pod_required=[[False, False], [False, True]],
            group_labels=[[True, True], [True, False]],
            n_taints=2,
            n_labels=2,
        )
        out = PB.binpack_pallas(inputs, buckets=8, tile_p=8, interpret=True)
        assert out.assigned.tolist() == [1, 0]

    def test_all_unschedulable(self):
        inputs = make_inputs(
            pod_requests=[[9, 9]], group_allocatable=[[1, 1]]
        )
        out = PB.binpack_pallas(inputs, buckets=8, tile_p=8, interpret=True)
        assert out.assigned.tolist() == [-1]
        assert int(out.unschedulable) == 1
        assert out.nodes_needed.tolist() == [0]

    def test_fused_stage_outputs(self):
        """Histogram and demand from the kernel match a NumPy recomputation."""
        rng = np.random.default_rng(7)
        inputs = random_inputs(rng, pods=130, types=9)
        buckets = 12
        assigned, hist, demand = PB.fused_assign(
            inputs, buckets=buckets, tile_p=64, interpret=True
        )
        assigned = np.asarray(assigned)
        req = np.asarray(inputs.pod_requests)
        alloc = np.asarray(inputs.group_allocatable)
        want_hist = np.zeros((alloc.shape[0], buckets), np.int64)
        want_demand = np.zeros_like(alloc, dtype=np.float64)
        for p, t in enumerate(assigned):
            if t < 0:
                continue
            shares = [
                (req[p, r] / alloc[t, r]) if alloc[t, r] > 0 else
                (0.0 if req[p, r] <= 0 else np.inf)
                for r in range(req.shape[1])
            ]
            b = int(np.clip(np.ceil(max(shares) * buckets), 1, buckets))
            want_hist[t, b - 1] += 1
            want_demand[t] += req[p]
        np.testing.assert_array_equal(np.asarray(hist), want_hist)
        np.testing.assert_allclose(
            np.asarray(demand), want_demand, rtol=1e-5, atol=1e-4
        )


@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="needs a real TPU: exercises the compiled Mosaic path "
    "(interpret=False); the CPU suite covers the same kernel logic in "
    "interpreter mode",
)
class TestCompiledMosaic:
    """VERDICT r1 weak#3: the Pallas kernel must be proven compiled on
    hardware, not only interpreted. Run manually on a TPU host with
    JAX_PLATFORMS unset (the CPU-forced suite skips this)."""

    def test_compiled_equals_xla_on_tpu(self):
        rng = np.random.default_rng(5)
        inputs = random_inputs(rng, pods=512, types=24)
        xla = B.binpack(inputs, buckets=16)
        pallas = PB.binpack_pallas(
            inputs, buckets=16, tile_p=128, interpret=False
        )
        assert_outputs_equal(xla, pallas)

    def test_compiled_weighted_equals_xla_on_tpu(self):
        """The encoder always emits pod_weight now, so the WEIGHTED path
        is the production Mosaic path — pin it compiled too.

        Weights are drawn from [1000, 5000): past bf16's 8-bit mantissa,
        so this FAILS if the hist/demand accumulators drop to the MXU's
        default bf16 operand rounding (small weights would round
        losslessly and mask it) — production dedup multiplicities at
        bench scale are ~4000/row."""
        import dataclasses

        rng = np.random.default_rng(6)
        weighted = dataclasses.replace(
            random_inputs(rng, pods=512, types=24),
            pod_weight=jnp.asarray(
                rng.integers(1000, 5000, 512).astype(np.int32)
            ),
        )
        xla = B.binpack(weighted, buckets=16)
        pallas = PB.binpack_pallas(
            weighted, buckets=16, tile_p=128, interpret=False
        )
        assert_outputs_equal(xla, pallas)

    def test_compiled_score_equals_xla_on_tpu(self):
        """The preference-score operand compiles through Mosaic and
        matches XLA on hardware (max-score + min-index selection)."""
        import dataclasses

        rng = np.random.default_rng(9)
        inputs = dataclasses.replace(
            random_inputs(rng, pods=512, types=24),
            pod_group_score=jnp.asarray(
                rng.integers(0, 100, (512, 24)).astype(np.float32)
            ),
            pod_weight=jnp.asarray(
                rng.integers(1000, 5000, 512).astype(np.int32)
            ),
        )
        xla = B.binpack(inputs, buckets=16)
        pallas = PB.binpack_pallas(
            inputs, buckets=16, tile_p=128, interpret=False
        )
        assert_outputs_equal(xla, pallas)

    def test_compiled_forbidden_equals_xla_on_tpu(self):
        """The affinity mask input compiles through Mosaic (one more
        [TILE_P, T] VMEM operand) and matches XLA on hardware."""
        import dataclasses

        rng = np.random.default_rng(8)
        inputs = dataclasses.replace(
            random_inputs(rng, pods=512, types=24),
            pod_group_forbidden=jnp.asarray(rng.random((512, 24)) < 0.3),
            pod_weight=jnp.asarray(
                rng.integers(1000, 5000, 512).astype(np.int32)
            ),
        )
        xla = B.binpack(inputs, buckets=16)
        pallas = PB.binpack_pallas(
            inputs, buckets=16, tile_p=128, interpret=False
        )
        assert_outputs_equal(xla, pallas)

    def test_compiled_exclusive_equals_xla_on_tpu(self):
        """The hostname self-anti-affinity flag compiles through Mosaic
        (one [TILE_P, 1] VMEM operand) and matches XLA on hardware."""
        import dataclasses

        rng = np.random.default_rng(10)
        inputs = dataclasses.replace(
            random_inputs(rng, pods=512, types=24),
            pod_exclusive=jnp.asarray(rng.random(512) < 0.3),
            pod_weight=jnp.asarray(
                rng.integers(1000, 5000, 512).astype(np.int32)
            ),
        )
        xla = B.binpack(inputs, buckets=16)
        pallas = PB.binpack_pallas(
            inputs, buckets=16, tile_p=128, interpret=False
        )
        assert_outputs_equal(xla, pallas)


class TestWeightedPallas:
    def test_weighted_matches_xla(self):
        import dataclasses

        rng = np.random.default_rng(9)
        inputs = random_inputs(rng, pods=90, types=7)
        weighted = dataclasses.replace(
            inputs,
            pod_weight=jnp.asarray(rng.integers(0, 9, 90).astype(np.int32)),
        )
        xla = B.binpack(weighted, buckets=12)
        pallas = PB.binpack_pallas(
            weighted, buckets=12, tile_p=64, interpret=True
        )
        assert_outputs_equal(xla, pallas)
