"""Manifest codec + docs/examples driven through the control plane.

reference: the envtest suites parse docs/examples/*.yaml and drive the real
manifests through the system (pkg/test/environment/namespace.go:57-83);
JSON-tag fidelity per the kubebuilder markers on the Go API structs.
"""

import glob
import os

import pytest

# register validators for the provider types the examples use
import karpenter_tpu.cloudprovider.aws  # noqa: F401
import karpenter_tpu.cloudprovider.tpu  # noqa: F401
from karpenter_tpu.api.metricsproducer import MetricsProducer
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroup
from karpenter_tpu.api.serialization import (
    camel_to_snake,
    dump_yaml,
    from_manifest,
    load_yaml,
    load_yaml_file,
    snake_to_camel,
    to_dict,
)
from karpenter_tpu.cloudprovider.fake import FakeFactory
from karpenter_tpu.runtime import KarpenterRuntime

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "examples",
)


def example_files():
    files = sorted(glob.glob(os.path.join(EXAMPLES, "*.yaml")))
    assert files, "docs/examples must not be empty"
    return files


class TestKeyMapping:
    @pytest.mark.parametrize(
        "camel,snake",
        [
            ("scaleTargetRef", "scale_target_ref"),
            ("minReplicas", "min_replicas"),
            ("defaultReplicas", "default_replicas"),
            ("nodeSelector", "node_selector"),
            ("id", "id"),
        ],
    )
    def test_roundtrip(self, camel, snake):
        assert camel_to_snake(camel) == snake
        assert snake_to_camel(snake) == camel


class TestExamples:
    @pytest.mark.parametrize("path", example_files())
    def test_loads_and_validates(self, path):
        objects = load_yaml_file(path)
        assert len(objects) >= 2
        for obj in objects:
            obj.validate()

    @pytest.mark.parametrize("path", example_files())
    def test_roundtrip_stable(self, path):
        objects = load_yaml_file(path)
        text = dump_yaml(*objects)
        again = load_yaml(text)
        assert dump_yaml(*again) == text

    def test_example_kinds(self):
        kinds = {
            type(o).__name__
            for path in example_files()
            for o in load_yaml_file(path)
        }
        assert kinds == {
            "HorizontalAutoscaler",
            "MetricsProducer",
            "ScalableNodeGroup",
        }


class TestCodecPosture:
    def test_unknown_field_rejected(self):
        with pytest.raises(ValueError) as e:
            from_manifest(
                {
                    "apiVersion": "autoscaling.karpenter.sh/v1alpha1",
                    "kind": "ScalableNodeGroup",
                    "metadata": {"name": "x"},
                    "spec": {"replicaz": 3},
                }
            )
        assert "replicaz" in str(e.value)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            from_manifest({"kind": "Widget"})

    def test_pod_affinity_roundtrip(self):
        """core/v1 nodeAffinity manifest dialect hydrates reflectively
        (requiredDuringSchedulingIgnoredDuringExecution and all)."""
        pod = from_manifest(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {"name": "p"},
                "spec": {
                    "containers": [{"requests": {"cpu": "1"}}],
                    "affinity": {
                        "nodeAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "nodeSelectorTerms": [
                                    {
                                        "matchExpressions": [
                                            {
                                                "key": "zone",
                                                "operator": "NotIn",
                                                "values": ["z1", "z2"],
                                            }
                                        ]
                                    }
                                ]
                            }
                        }
                    },
                },
            }
        )
        from karpenter_tpu.api.core import (
            affinity_shape,
            matches_affinity_shape,
        )

        shape = affinity_shape(pod.spec.affinity)
        assert shape == ((("zone", "NotIn", ("z1", "z2")),),)
        assert matches_affinity_shape({"zone": "z3"}, shape)
        assert not matches_affinity_shape({"zone": "z1"}, shape)
        from karpenter_tpu.api.serialization import to_dict

        doc = to_dict(pod)
        terms = doc["spec"]["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"]
        assert terms[0]["matchExpressions"][0]["operator"] == "NotIn"

    def test_pod_anti_affinity_roundtrip(self):
        """core/v1 podAntiAffinity/podAffinity manifest dialect hydrates
        reflectively, and the SELF-matching slice canonicalizes into
        pod_affinity_shape (solver model scope); foreign hostname anti
        terms fall out entirely — a scale-up's fresh nodes can never be
        blocked by them — while non-hostname foreign terms canonicalize
        into the shape's foreign slice."""
        pod = from_manifest(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": "db-0",
                    "namespace": "prod",
                    "labels": {"app": "db"},
                },
                "spec": {
                    "containers": [{"requests": {"cpu": "1"}}],
                    "affinity": {
                        "podAntiAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": [
                                {
                                    "labelSelector": {
                                        "matchLabels": {"app": "db"}
                                    },
                                    "topologyKey": "kubernetes.io/hostname",
                                },
                                {
                                    "labelSelector": {
                                        "matchExpressions": [
                                            {
                                                "key": "app",
                                                "operator": "In",
                                                "values": ["db"],
                                            }
                                        ]
                                    },
                                    "topologyKey": "topology.kubernetes.io/zone",
                                },
                                {
                                    # matches OTHER pods only: out of scope
                                    "labelSelector": {
                                        "matchLabels": {"app": "web"}
                                    },
                                    "topologyKey": "kubernetes.io/hostname",
                                },
                                {
                                    # own selector, FOREIGN namespace scope
                                    "labelSelector": {
                                        "matchLabels": {"app": "db"}
                                    },
                                    "topologyKey": "kubernetes.io/hostname",
                                    "namespaces": ["elsewhere"],
                                },
                            ]
                        },
                        "podAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": [
                                {
                                    "labelSelector": {
                                        "matchLabels": {"app": "db"}
                                    },
                                    "topologyKey": "topology.kubernetes.io/region",
                                }
                            ]
                        },
                    },
                },
            }
        )
        from karpenter_tpu.api.core import pod_affinity_shape

        shape = pod_affinity_shape(
            pod.spec.affinity, pod.metadata.labels, pod.metadata.namespace
        )
        assert shape == (
            1,  # hostname exclusive (self-matching term #1)
            ("topology.kubernetes.io/zone",),  # domain cap (term #2)
            ("topology.kubernetes.io/region",),  # co-location
            # workload identity: namespace + the canonical SELECTOR
            # forms of the domain-relevant terms (zone matchExpressions,
            # region matchLabels) — selector-keyed so StatefulSet
            # per-pod labels don't fragment the anti-group
            (
                "prod",
                (
                    ((), (("app", "In", ("db",)),)),
                    ((("app", "db"),), ()),
                ),
            ),
            # foreign slice: both foreign terms here are hostname ANTI
            # (never constraining on fresh nodes) -> empty
            (),
        )
        from karpenter_tpu.api.serialization import to_dict

        doc = to_dict(pod)
        terms = doc["spec"]["affinity"]["podAntiAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]
        assert terms[0]["topologyKey"] == "kubernetes.io/hostname"
        assert terms[3]["namespaces"] == ["elsewhere"]

    def test_pod_preferred_affinity_roundtrip(self):
        pod = from_manifest(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {"name": "p"},
                "spec": {
                    "containers": [{"requests": {"cpu": "1"}}],
                    "affinity": {
                        "nodeAffinity": {
                            "preferredDuringSchedulingIgnoredDuringExecution": [
                                {
                                    "weight": 80,
                                    "preference": {
                                        "matchExpressions": [
                                            {
                                                "key": "disk",
                                                "operator": "In",
                                                "values": ["ssd"],
                                            }
                                        ]
                                    },
                                }
                            ]
                        }
                    },
                },
            }
        )
        from karpenter_tpu.api.core import preference_score, preferred_shape

        shape = preferred_shape(pod.spec.affinity)
        assert preference_score({"disk": "ssd"}, shape) == 80
        assert preference_score({"disk": "hdd"}, shape) == 0
        from karpenter_tpu.api.serialization import to_dict

        doc = to_dict(pod)
        pref = doc["spec"]["affinity"]["nodeAffinity"][
            "preferredDuringSchedulingIgnoredDuringExecution"
        ]
        assert pref[0]["weight"] == 80

    def test_pod_init_containers_and_overhead_roundtrip(self):
        """core/v1 manifest dialect: initContainers + overhead hydrate and
        dump, and effective_requests reflects them."""
        pod = from_manifest(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {"name": "p"},
                "spec": {
                    "containers": [
                        {"name": "main", "requests": {"cpu": "500m"}}
                    ],
                    "initContainers": [
                        {"name": "init", "requests": {"cpu": "2"}}
                    ],
                    "overhead": {"memory": "64Mi"},
                },
            }
        )
        assert str(pod.effective_requests()["cpu"]) == "2"
        assert str(pod.effective_requests()["memory"]) == "64Mi"
        from karpenter_tpu.api.serialization import to_dict

        doc = to_dict(pod)
        assert doc["spec"]["initContainers"][0]["requests"]["cpu"] == "2"
        assert doc["spec"]["overhead"]["memory"] == "64Mi"

    def test_core_kind_wrong_api_version_rejected(self):
        with pytest.raises(ValueError):
            from_manifest(
                {
                    "apiVersion": "apps/v1",
                    "kind": "Node",
                    "metadata": {"name": "n"},
                }
            )

    def test_core_kinds_dump_core_api_version(self):
        """Node/Pod are core/v1 kinds: stamping the autoscaling group on
        them would make the manifests invalid for kubectl-shaped tooling."""
        from karpenter_tpu.api.core import Node, ObjectMeta
        from karpenter_tpu.api.serialization import to_dict

        doc = to_dict(Node(metadata=ObjectMeta(name="n")))
        assert doc["apiVersion"] == "v1"
        assert doc["kind"] == "Node"

    def test_autoscaling_kinds_dump_group_api_version(self):
        from karpenter_tpu.api.scalablenodegroup import (
            ScalableNodeGroup,
            ScalableNodeGroupSpec,
        )
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.serialization import to_dict

        doc = to_dict(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(type="AWSEC2AutoScalingGroup", id="x"),
            )
        )
        assert doc["apiVersion"] == "autoscaling.karpenter.sh/v1alpha1"

    def test_wrong_api_version_rejected(self):
        with pytest.raises(ValueError):
            from_manifest(
                {
                    "apiVersion": "autoscaling.karpenter.sh/v2",
                    "kind": "MetricsProducer",
                }
            )

    def test_envelope_on_dump(self):
        sng = ScalableNodeGroup()
        sng.metadata.name = "n"
        d = to_dict(sng)
        assert d["apiVersion"] == "autoscaling.karpenter.sh/v1alpha1"
        assert d["kind"] == "ScalableNodeGroup"

    def test_internal_metadata_not_serialized(self):
        sng = ScalableNodeGroup()
        sng.metadata.name = "n"
        sng.metadata.uid = "uid-9"
        sng.metadata.resource_version = 7
        text = dump_yaml(sng)
        assert "uid" not in text
        assert "resourceVersion" not in text


class TestQueueExampleEndToEnd:
    """The queue-length example converges exactly like the reference's HA
    suite: 41 messages / target 4 (AverageValue) -> 11 replicas."""

    def test_converges(self):
        provider = FakeFactory()
        runtime = KarpenterRuntime(cloud_provider_factory=provider)
        objects = load_yaml_file(
            os.path.join(EXAMPLES, "queue-length-average-value.yaml")
        )
        for obj in objects:
            # swap provider-specific bits for the fake provider
            if isinstance(obj, MetricsProducer):
                obj.spec.queue.type = "FakeQueue"
                obj.spec.queue.id = "q1"
            if isinstance(obj, ScalableNodeGroup):
                obj.spec.type = "FakeNodeGroup"
                obj.spec.id = "ng1"
            runtime.store.create(obj)
        provider.queue_lengths["q1"] = 41
        provider.node_replicas["ng1"] = 1
        # fix up the HA query to the fake producer's gauge labels
        ha = runtime.store.get(
            "HorizontalAutoscaler", "default", "ml-training-capacity-autoscaler"
        )
        ha.spec.metrics[0].prometheus.query = (
            'karpenter_queue_length{name="ml-training-queue"}'
        )
        runtime.store.update(ha)

        runtime.manager.converge()
        sng = runtime.store.get(
            "ScalableNodeGroup", "default", "ml-training-capacity"
        )
        assert sng.spec.replicas == 11
        assert provider.node_replicas["ng1"] == 11
        ha = runtime.store.get(
            "HorizontalAutoscaler", "default", "ml-training-capacity-autoscaler"
        )
        assert ha.status.desired_replicas == 11


class TestExamplesConverge:
    def test_all_examples_reconcile_in_one_runtime(self):
        """Kitchen sink: EVERY shipped example manifest loaded into ONE
        control plane, fake provider seeded for each referenced id, and
        the whole fleet reconciled to happy conditions — examples are not
        just parseable, they run (the reference's envtest suites drive
        the same files, pkg/test/environment/namespace.go:57-83)."""
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime

        provider = FakeFactory()
        clock = {"now": 1000.0}
        runtime = KarpenterRuntime(
            cloud_provider_factory=provider,
            clock=lambda: clock["now"],
        )
        objects = [
            obj
            for path in example_files()
            for obj in load_yaml_file(path)
        ]
        for obj in objects:
            if type(obj).__name__ == "ScalableNodeGroup":
                provider.node_replicas[obj.spec.id] = obj.spec.replicas or 1
            if (
                type(obj).__name__ == "MetricsProducer"
                and obj.spec.queue is not None
            ):
                provider.queue_lengths[obj.spec.queue.id] = 8
            runtime.store.create(obj)

        # enough ticks for every subsystem an example opts into to warm
        # up — the forecast example's minSamples gate needs 6 observed
        # ticks before its Forecasting condition goes True
        for _ in range(8):
            runtime.manager.reconcile_all()
            clock["now"] += 61

        unhappy = []
        for obj in objects:
            fresh = runtime.store.get(
                type(obj).__name__, obj.metadata.namespace, obj.metadata.name
            )
            if not fresh.status_conditions().is_happy():
                unhappy.append(
                    (
                        type(obj).__name__,
                        obj.metadata.name,
                        [
                            (c.type, c.status, c.message)
                            for c in fresh.status.conditions
                            if c.status != "True"
                        ],
                    )
                )
        assert not unhappy, unhappy
