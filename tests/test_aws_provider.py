"""AWS provider parity tests.

Table cases mirror the reference's unit suites: ASG ARN normalization
(pkg/cloudprovider/aws/autoscalinggroup_test.go:20-47), SQS queue length
happy/error (sqsqueue_test.go:27-64), MNG ready-node counting
(suite_test.go:45-62), plus transient-error classification (error.go:28-55)
flowing through the ScalableNodeGroup controller.
"""

import pytest

from karpenter_tpu.api.core import (
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    resource_list,
)
from karpenter_tpu.api.metricsproducer import (
    AWS_SQS_QUEUE_TYPE,
    QueueSpec,
    validate_queue,
)
from karpenter_tpu.api.scalablenodegroup import (
    AWS_EC2_AUTO_SCALING_GROUP,
    AWS_EKS_NODE_GROUP,
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.cloudprovider import Options
from karpenter_tpu.cloudprovider.aws import (
    AWSAPIError,
    AWSFactory,
    AutoScalingGroup,
    ManagedNodeGroup,
    NODE_GROUP_LABEL,
    SQSQueue,
    normalize_asg_id,
    parse_arn,
    parse_mng_id,
    transient_error,
)
from karpenter_tpu.controllers.errors import error_code, is_retryable
from karpenter_tpu.runtime import KarpenterRuntime
from karpenter_tpu.store import Store

ASG_ARN = (
    "arn:aws:autoscaling:region:123456789012:"
    "autoScalingGroup:uuid:autoScalingGroupName/asg-name"
)
MNG_ARN = (
    "arn:aws:eks:us-west-2:741206201142:"
    "nodegroup/ridiculous-sculpture-1594766004/ng-0b663e8a/aeb9a7fe"
)
SQS_ARN = "arn:aws:iam:us-west-2:112358132134:fibonacci"


# --- fakes mirroring pkg/cloudprovider/aws/fake/ ---------------------------


class FakeAutoscalingAPI:
    def __init__(self, instances=(), want_err=None):
        self.instances = list(instances)
        self.want_err = want_err
        self.updated = None

    def describe_auto_scaling_groups(self, names, max_records):
        if self.want_err:
            raise self.want_err
        return [{"instances": self.instances}]

    def update_auto_scaling_group(self, name, desired_capacity):
        if self.want_err:
            raise self.want_err
        self.updated = (name, desired_capacity)


class FakeEKSAPI:
    def __init__(self, want_err=None):
        self.want_err = want_err
        self.updated = None

    def update_nodegroup_config(
        self, cluster_name, nodegroup_name, desired_size
    ):
        if self.want_err:
            raise self.want_err
        self.updated = (cluster_name, nodegroup_name, desired_size)


class FakeSQSAPI:
    def __init__(self, url="oopsydaisy", attributes=None, want_err=None,
                 messages=None):
        self.url = url
        self.attributes = attributes or {}
        self.want_err = want_err
        self.messages = messages or []
        self.receive_calls = []

    def get_queue_url(self, queue_name, account_id):
        self.url_calls = getattr(self, "url_calls", 0) + 1
        if self.want_err:
            raise self.want_err
        return self.url

    def get_queue_attributes(self, queue_url, attribute_names):
        if self.want_err:
            raise self.want_err
        return self.attributes

    def receive_message(self, queue_url, attribute_names,
                        max_number_of_messages, visibility_timeout):
        if self.want_err:
            raise self.want_err
        self.receive_calls.append(
            (queue_url, tuple(attribute_names), max_number_of_messages,
             visibility_timeout)
        )
        return self.messages[:max_number_of_messages]


# --- ARN tables (reference: autoscalinggroup_test.go:20-47) ----------------


class TestNormalizeASGID:
    @pytest.mark.parametrize(
        "value,expected",
        [
            ("", ""),
            ("foo", "foo"),
            (ASG_ARN, "asg-name"),
            (
                "arn:aws:autoscaling:region:123456789012:"
                "autoScalingGroup:uuid:autoScalingGroupName/",
                "",
            ),
        ],
    )
    def test_ok(self, value, expected):
        assert normalize_asg_id(value) == expected

    @pytest.mark.parametrize(
        "value",
        [
            # missing the name specifier entirely
            "arn:aws:autoscaling:region:123456789012:"
            "autoScalingGroup:uuid:autoScalingGroupName",
            # misspelled specifier
            "arn:aws:autoscaling:region:123456789012:"
            "autoScalingGroup:uuid:utoScalingGroupName/asg-name",
            "arn:aws:autoscalin:region:123456789012:"
            "autoScalingGroup:uuid:utoScalingGroupName/asg-name",
        ],
    )
    def test_invalid(self, value):
        with pytest.raises(ValueError):
            normalize_asg_id(value)


class TestParseMNGID:
    def test_extracts_cluster_and_nodegroup(self):
        assert parse_mng_id(MNG_ARN) == (
            "ridiculous-sculpture-1594766004",
            "ng-0b663e8a",
        )

    @pytest.mark.parametrize("value", ["not-an-arn", "arn:aws:eks:r:a:flat"])
    def test_invalid(self, value):
        with pytest.raises(ValueError):
            parse_mng_id(value)


class TestParseArn:
    def test_resource_keeps_colons(self):
        assert (
            parse_arn(ASG_ARN).resource
            == "autoScalingGroup:uuid:autoScalingGroupName/asg-name"
        )

    def test_fields(self):
        arn = parse_arn(SQS_ARN)
        assert arn.account_id == "112358132134"
        assert arn.resource == "fibonacci"


# --- ASG replica semantics (reference: autoscalinggroup.go:79-108) ---------


class TestAutoScalingGroup:
    def test_stabilized_from_desired_capacity(self):
        """Beats the reference's TODO-true Stabilized: unstable while the
        ASG converges toward desired, stable once every desired instance
        is Healthy+InService; clients without desired_capacity keep the
        reference behavior."""

        class DescribeAPI(FakeAutoscalingAPI):
            def __init__(self, instances, desired=None):
                super().__init__(instances)
                self.desired = desired

            def describe_auto_scaling_groups(self, names, max_records):
                group = {"instances": self.instances}
                if self.desired is not None:
                    group["desired_capacity"] = self.desired
                return [group]

        healthy = {"health_status": "Healthy", "lifecycle_state": "InService"}
        pending = {"health_status": "Healthy", "lifecycle_state": "Pending"}
        converging = AutoScalingGroup(
            "asg", DescribeAPI([healthy, pending], desired=2)
        )
        stable, message = converging.stabilized()
        assert not stable and "1/2" in message
        settled = AutoScalingGroup(
            "asg", DescribeAPI([healthy, healthy], desired=2)
        )
        assert settled.stabilized() == (True, "")
        legacy = AutoScalingGroup("asg", DescribeAPI([pending]))
        assert legacy.stabilized() == (True, "")

    def test_one_describe_per_reconcile_instance(self):
        """stabilized() + get_replicas() on one (per-reconcile) instance
        must cost ONE DescribeAutoScalingGroups call, not two."""

        class CountingAPI(FakeAutoscalingAPI):
            calls = 0

            def describe_auto_scaling_groups(self, names, max_records):
                CountingAPI.calls += 1
                return [{"instances": self.instances, "desired_capacity": 0}]

        group = AutoScalingGroup("asg", CountingAPI())
        group.stabilized()
        group.get_replicas()
        assert CountingAPI.calls == 1

    def test_counts_only_healthy_in_service(self):
        api = FakeAutoscalingAPI(
            instances=[
                {"health_status": "Healthy", "lifecycle_state": "InService"},
                {"health_status": "Healthy", "lifecycle_state": "Pending"},
                {"health_status": "Unhealthy", "lifecycle_state": "InService"},
                {"health_status": "Healthy", "lifecycle_state": "InService"},
            ]
        )
        assert AutoScalingGroup(ASG_ARN, api).get_replicas() == 2

    def test_set_replicas_uses_normalized_name(self):
        api = FakeAutoscalingAPI()
        AutoScalingGroup(ASG_ARN, api).set_replicas(7)
        assert api.updated == ("asg-name", 7)

    def test_missing_group_names_the_condition(self):
        """An empty describe means the group does not exist — the error
        must say so, not claim the group 'has no instances' (a healthy
        scaled-to-zero group also has no instances)."""

        class EmptyAPI(FakeAutoscalingAPI):
            def describe_auto_scaling_groups(self, names, max_records):
                return []

        with pytest.raises(RuntimeError, match="not found"):
            AutoScalingGroup("my-asg", EmptyAPI()).get_replicas()

    def test_ambiguous_group_names_the_condition(self):
        class DoubleAPI(FakeAutoscalingAPI):
            def describe_auto_scaling_groups(self, names, max_records):
                return [{"instances": []}, {"instances": []}]

        with pytest.raises(RuntimeError, match="ambiguous"):
            AutoScalingGroup("my-asg", DoubleAPI()).get_replicas()

    def test_api_error_is_transient(self):
        api = FakeAutoscalingAPI(
            want_err=AWSAPIError("throttled", code="ThrottlingException")
        )
        asg = AutoScalingGroup("my-asg", api)
        with pytest.raises(Exception) as e:
            asg.get_replicas()
        assert is_retryable(e.value)
        assert error_code(e.value) == "ThrottlingException"

    def test_non_retryable_code(self):
        api = FakeAutoscalingAPI(
            want_err=AWSAPIError("denied", code="AccessDenied")
        )
        with pytest.raises(Exception) as e:
            AutoScalingGroup("my-asg", api).get_replicas()
        assert not is_retryable(e.value)
        assert error_code(e.value) == "AccessDenied"


# --- MNG: store-observed replicas (reference: managednodegroup.go:86-110) --


def eks_node(name, nodegroup, ready=True, schedulable=True):
    return Node(
        metadata=ObjectMeta(
            name=name, labels={"eks.amazonaws.com/nodegroup": nodegroup}
        ),
        spec=NodeSpec(unschedulable=not schedulable),
        status=NodeStatus(
            allocatable=resource_list(cpu="4", memory="8Gi", pods="16"),
            conditions=[NodeCondition("Ready", "True" if ready else "False")],
        ),
    )


class TestManagedNodeGroup:
    def test_counts_ready_schedulable_labeled_nodes(self):
        store = Store()
        store.create(eks_node("n1", "ng-0b663e8a"))
        store.create(eks_node("n2", "ng-0b663e8a", ready=False))
        store.create(eks_node("n3", "ng-0b663e8a", schedulable=False))
        store.create(eks_node("n4", "other-group"))
        mng = ManagedNodeGroup(MNG_ARN, FakeEKSAPI(), store)
        assert mng.get_replicas() == 1

    def test_set_replicas_targets_cluster_and_group(self):
        api = FakeEKSAPI()
        ManagedNodeGroup(MNG_ARN, api, Store()).set_replicas(3)
        assert api.updated == (
            "ridiculous-sculpture-1594766004",
            "ng-0b663e8a",
            3,
        )


# --- SQS (reference: sqsqueue_test.go:27-64) -------------------------------


class TestSQSQueue:
    def test_length(self):
        api = FakeSQSAPI(
            attributes={"ApproximateNumberOfMessages": "42"}
        )
        assert SQSQueue(SQS_ARN, api).length() == 42

    def test_length_error(self):
        api = FakeSQSAPI(want_err=RuntimeError("didn't work"))
        with pytest.raises(RuntimeError):
            SQSQueue(SQS_ARN, api).length()

    def test_oldest_age_empty_queue_is_zero(self):
        assert SQSQueue(SQS_ARN, FakeSQSAPI()).oldest_message_age_seconds() == 0

    def test_oldest_age_from_sent_timestamp_sampling(self):
        """Beyond the reference (sqsqueue.go:78-80 stubs this at 0): the
        age comes from peeking SentTimestamp with visibility_timeout=0 so
        sampling never consumes or hides messages from real consumers."""
        import time

        now_ms = int(time.time() * 1000)
        api = FakeSQSAPI(
            messages=[
                {"Attributes": {"SentTimestamp": str(now_ms - 90_000)}},
                {"Attributes": {"SentTimestamp": str(now_ms - 240_000)}},
                {"Attributes": {}},  # missing timestamp: skipped
            ]
        )
        age = SQSQueue(SQS_ARN, api).oldest_message_age_seconds()
        assert 239 <= age <= 242  # the OLDEST of the sample, ~240s
        (call,) = api.receive_calls
        assert call[1] == ("SentTimestamp",)
        assert call[3] == 0  # visibility_timeout: a peek, not a consume

    def test_oldest_age_sampling_is_rate_limited(self):
        """ReceiveMessage bumps ApproximateReceiveCount (redrive-policy
        fuel) even at visibility_timeout=0, so the 5s producer tick must
        NOT sample every time: one sample per age_sample_interval, with
        the cached age extrapolated by elapsed time in between."""
        clock = {"now": 1000.0}
        base_ms = int((clock["now"] - 100) * 1000)  # sent 100s ago
        api = FakeSQSAPI(
            messages=[{"Attributes": {"SentTimestamp": str(base_ms)}}]
        )
        queue = SQSQueue(
            SQS_ARN, api, age_sample_interval=60.0,
            clock=lambda: clock["now"],
        )
        assert queue.oldest_message_age_seconds() == 100
        clock["now"] += 30  # inside the interval: no new ReceiveMessage
        assert queue.oldest_message_age_seconds() == 130  # extrapolated
        assert len(api.receive_calls) == 1
        clock["now"] += 31  # past the interval: resample
        assert queue.oldest_message_age_seconds() == 161
        assert len(api.receive_calls) == 2

    def test_oldest_age_fresh_head_still_climbs(self):
        """A message whose sampled age rounds to 0 must still age between
        refreshes — only a sampled EMPTY queue stays pinned at 0 (a
        fresh-but-stuck message is exactly what the signal exists for)."""
        clock = {"now": 1000.0}
        api = FakeSQSAPI(
            messages=[
                {"Attributes": {"SentTimestamp": str(int(1000.0 * 1000))}}
            ]
        )
        queue = SQSQueue(
            SQS_ARN, api, age_sample_interval=60.0,
            clock=lambda: clock["now"],
        )
        assert queue.oldest_message_age_seconds() == 0  # fresh at sample
        clock["now"] += 45  # stuck unconsumed inside the interval
        assert queue.oldest_message_age_seconds() == 45
        assert len(api.receive_calls) == 1

        # sampled-empty stays 0 between refreshes
        clock["now"] += 30  # past the interval: resample, now empty
        api.messages = []
        assert queue.oldest_message_age_seconds() == 0
        clock["now"] += 45
        assert queue.oldest_message_age_seconds() == 0
        assert len(api.receive_calls) == 2

    def test_oldest_age_error_is_wrapped(self):
        api = FakeSQSAPI()
        queue = SQSQueue(SQS_ARN, api)
        queue._url()  # resolve first so the sampling call is what fails
        api.want_err = RuntimeError("throttled")
        with pytest.raises(RuntimeError, match="could not sample"):
            queue.oldest_message_age_seconds()

    def test_oldest_age_flows_to_gauge_and_status(self):
        """End-to-end through the queue producer: status + the
        karpenter_queue_oldest_message_age_seconds gauge."""
        import time

        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.metricsproducer import (
            MetricsProducer,
            MetricsProducerSpec,
        )
        from karpenter_tpu.metrics.producers.queue import QueueProducer
        from karpenter_tpu.metrics.registry import GaugeRegistry

        now_ms = int(time.time() * 1000)
        api = FakeSQSAPI(
            attributes={"ApproximateNumberOfMessages": "7"},
            messages=[{"Attributes": {"SentTimestamp": str(now_ms - 60_000)}}],
        )
        mp = MetricsProducer(
            metadata=ObjectMeta(name="q", namespace="default"),
            spec=MetricsProducerSpec(
                queue=QueueSpec(type=AWS_SQS_QUEUE_TYPE, id=SQS_ARN)
            ),
        )
        registry = GaugeRegistry()
        QueueProducer(mp, SQSQueue(SQS_ARN, api), registry).reconcile()
        assert mp.status.queue.length == 7
        assert 59 <= mp.status.queue.oldest_message_age_seconds <= 62
        gauge = registry.gauge("queue", "oldest_message_age_seconds")
        assert 59 <= gauge.get("q", "default") <= 62

    def test_queue_url_resolved_once(self):
        """The ARN->URL mapping is immutable: polling length repeatedly
        must not re-issue GetQueueUrl each time."""
        api = FakeSQSAPI(attributes={"ApproximateNumberOfMessages": "1"})
        queue = SQSQueue(SQS_ARN, api)
        for _ in range(3):
            assert queue.length() == 1
        assert api.url_calls == 1

    def test_queue_url_cache_spans_polls_via_factory(self):
        """Producers resolve queue_for every tick; the factory must hand
        back the same queue object so the URL cache actually helps."""
        api = FakeSQSAPI(attributes={"ApproximateNumberOfMessages": "1"})
        factory = AWSFactory(Options(store=Store()), sqs_client=api)
        spec = QueueSpec(type=AWS_SQS_QUEUE_TYPE, id=SQS_ARN)
        for _ in range(3):
            assert factory.queue_for(spec).length() == 1
        assert api.url_calls == 1


# --- admission validators + factory dispatch -------------------------------


class TestValidatorsAndFactory:
    def test_asg_spec_validation(self):
        ScalableNodeGroup(
            metadata=ObjectMeta(name="ok"),
            spec=ScalableNodeGroupSpec(
                type=AWS_EC2_AUTO_SCALING_GROUP, id=ASG_ARN
            ),
        ).validate()

    def test_mng_spec_validation_rejects_bad_arn(self):
        sng = ScalableNodeGroup(
            metadata=ObjectMeta(name="bad"),
            spec=ScalableNodeGroupSpec(type=AWS_EKS_NODE_GROUP, id="nope"),
        )
        with pytest.raises(Exception):
            sng.validate()

    def test_sqs_queue_validation(self):
        validate_queue(QueueSpec(type=AWS_SQS_QUEUE_TYPE, id=SQS_ARN))
        with pytest.raises(Exception):
            validate_queue(QueueSpec(type=AWS_SQS_QUEUE_TYPE, id="not-arn"))

    def test_factory_dispatch(self):
        store = Store()
        factory = AWSFactory(
            Options(store=store),
            autoscaling_client=FakeAutoscalingAPI(),
            eks_client=FakeEKSAPI(),
            sqs_client=FakeSQSAPI(),
        )
        asg = factory.node_group_for(
            ScalableNodeGroupSpec(type=AWS_EC2_AUTO_SCALING_GROUP, id="x")
        )
        mng = factory.node_group_for(
            ScalableNodeGroupSpec(type=AWS_EKS_NODE_GROUP, id=MNG_ARN)
        )
        q = factory.queue_for(QueueSpec(type=AWS_SQS_QUEUE_TYPE, id=SQS_ARN))
        assert isinstance(asg, AutoScalingGroup)
        assert isinstance(mng, ManagedNodeGroup)
        assert isinstance(q, SQSQueue)

    def test_unbound_client_fails_with_guidance(self):
        factory = AWSFactory(Options(store=Store()))
        asg = factory.node_group_for(
            ScalableNodeGroupSpec(type=AWS_EC2_AUTO_SCALING_GROUP, id="x")
        )
        with pytest.raises(Exception) as e:
            asg.get_replicas()
        assert "API client bound" in str(e.value.__cause__ or e.value)

    def test_registry_selects_aws(self):
        from karpenter_tpu.cloudprovider.registry import new_factory

        factory = new_factory(Options(store=Store()), provider="aws")
        assert isinstance(factory, AWSFactory)


# --- transient errors keep the resource Active (controller.go:83-95) -------


class TestRetryableThroughController:
    def test_throttle_keeps_sng_active(self):
        store = Store()
        api = FakeAutoscalingAPI(
            want_err=AWSAPIError("throttled", code="ThrottlingException")
        )
        provider = AWSFactory(Options(store=store), autoscaling_client=api)
        runtime = KarpenterRuntime(
            store=store, cloud_provider_factory=provider
        )
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="asg"),
                spec=ScalableNodeGroupSpec(
                    type=AWS_EC2_AUTO_SCALING_GROUP, id="my-asg", replicas=3
                ),
            )
        )
        runtime.manager.reconcile_all()
        sng = store.get("ScalableNodeGroup", "default", "asg")
        conditions = sng.status_conditions()
        active = conditions.get("Active")
        assert active is not None and active.status == "True"
        able = conditions.get("AbleToScale")
        assert able is not None and able.status == "False"

    def test_transient_error_none_passthrough(self):
        assert transient_error(None) is None


class TestNodeTemplates:
    """Scale-from-zero: both AWS node-group kinds surface a NodeTemplate
    when the injected client implements the optional describe hook, with
    EKS-dialect taint enums converted to core/v1."""

    def test_asg_without_hook_returns_none(self):
        group = AutoScalingGroup("my-asg", FakeAutoscalingAPI())
        assert group.template() is None

    def test_unbound_client_reads_as_no_template(self):
        """The no-client-bound default (_NotImplementedClient) has a
        catch-all __getattr__; the optional template hook must still
        read as ABSENT — 'no declared shape', not a per-tick error."""
        from karpenter_tpu.cloudprovider.aws import AWSFactory

        factory = AWSFactory()  # no clients injected
        group = factory.node_group_for(
            type(
                "Spec", (), {"type": "AWSEC2AutoScalingGroup", "id": "asg"}
            )()
        )
        assert group.template() is None

    def test_asg_template_from_hook(self):
        class TemplateAPI(FakeAutoscalingAPI):
            def describe_node_template(self, name):
                assert name == "my-asg"
                return {
                    "allocatable": {"cpu": "8", "memory": "32Gi"},
                    "labels": {"node.kubernetes.io/instance-type": "m5.2xlarge"},
                }

        template = AutoScalingGroup("my-asg", TemplateAPI()).template()
        assert template.allocatable["cpu"].to_float() == 8
        assert (
            template.labels["node.kubernetes.io/instance-type"]
            == "m5.2xlarge"
        )

    def test_mng_template_stamps_group_label_and_converts_taints(self):
        class TemplateAPI(FakeEKSAPI):
            def describe_node_template(self, cluster, nodegroup):
                assert (cluster, nodegroup) == ("cluster", "group")
                return {
                    "allocatable": {"cpu": "4"},
                    "taints": [
                        {"key": "gpu", "value": "true", "effect": "NO_SCHEDULE"}
                    ],
                }

        group = ManagedNodeGroup(
            "arn:aws:eks:us-east-1:1234:nodegroup/cluster/group/uuid",
            TemplateAPI(),
            Store(),
        )
        template = group.template()
        assert template.labels[NODE_GROUP_LABEL] == "group"
        assert [(t.key, t.effect) for t in template.taints] == [
            ("gpu", "NoSchedule")
        ]

    def test_asg_hook_error_classified_like_reads(self):
        """Hook failures flow through transient_error, so an SDK-shaped
        throttle is retryable and keeps the resource Active."""
        from karpenter_tpu.controllers.errors import is_retryable

        class SDKError(RuntimeError):
            code = "Throttling"

        class ThrowingAPI(FakeAutoscalingAPI):
            def describe_node_template(self, name):
                raise SDKError("throttled")

        try:
            AutoScalingGroup("my-asg", ThrowingAPI()).template()
        except Exception as e:  # noqa: BLE001
            assert is_retryable(e)
        else:
            raise AssertionError("expected transient error")
