"""Decision provenance ledger (observability/provenance.py).

The acceptance pins (ISSUE 12 / docs/observability.md "Decision
provenance"):

  * /debug/decisions answers the provenance question end to end in a
    seeded multi-tenant replay: for a pinned tick, the ledger record
    for a chosen HA names the winning stage, the solver rung used, and
    a trace id that resolves in the exported trace JSONL;
  * a disabled ledger (--provenance off, the default posture) yields
    BYTE-IDENTICAL decisions and a mark-free hot path (records_total
    stays 0) — the same property the tracing-off pin established;
  * the ring is columnar and bounded: batch appends, oldest-drop,
    filtered queries, crash-safe JSONL export;
  * overhead stays bounded (the structural guard; `make
    bench-provenance` publishes the honest <=5% number).
"""

import json
import urllib.request

import numpy as np
import pytest

from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.observability import (
    DecisionLedger,
    MetricsServer,
    default_ledger,
    reset_default_ledger,
    set_default_ledger,
)
from karpenter_tpu.observability.provenance import (
    STAGE_ADMISSION_DEFERRAL,
    STAGE_COST_BLIND,
    STAGE_COST_CLAMP,
    STAGE_COST_RAISE,
    STAGE_DEGRADED_FLOOR,
    STAGE_FORECAST_BLEND,
    STAGE_REACTIVE,
    decisions_export_path,
)


@pytest.fixture
def fresh_ledger():
    """Isolated process-default ledger (annotation sites read the
    default dynamically), ENABLED for the test."""
    saved = default_ledger()
    ledger = reset_default_ledger(enabled=True)
    yield ledger
    set_default_ledger(saved)


def _commit(ledger, kind="ha", n=1, **columns):
    batch = ledger.begin(kind, n, **columns)
    ledger.commit(batch)
    return batch


class TestDecisionLedger:
    def test_disabled_ledger_stages_nothing(self):
        ledger = DecisionLedger(enabled=False)
        assert ledger.begin("ha", 4, name="x") is None
        assert ledger.current() is None
        assert ledger.commit() == 0
        assert ledger.records_total == 0

    def test_columnar_batch_commit_and_query_filters(self):
        ledger = DecisionLedger(capacity=64, enabled=True)
        batch = ledger.begin(
            "ha", 3,
            tenant="t1",
            namespace=["default"] * 3,
            name=["a", "b", "c"],
            group=["g1", "g1", "g2"],
            observed=np.arange(12, dtype=np.float32).reshape(3, 4),
            observed_n=np.array([2, 1, 4], np.int16),
            prev_replicas=np.array([1, 2, 3], np.int32),
        )
        batch.annotate(
            base_desired=np.array([5, 2, 3], np.int32),
            final_desired=np.array([5, 2, 3], np.int32),
        )
        assert ledger.commit(batch) == 3
        assert ledger.records_total == 3
        assert len(ledger.query(group="g1")) == 2
        assert len(ledger.query(tenant="t1")) == 3
        assert len(ledger.query(tenant="nope")) == 0
        assert len(ledger.query(name="c")) == 1
        assert len(ledger.query(limit=1)) == 1
        record = ledger.query(name="a")[0]
        # observed values trim to the row's real metric count
        assert record["observed"] == [0.0, 1.0]
        assert record["prev_replicas"] == 1
        assert record["base_desired"] == 5
        # never-annotated numerics render as null, not sentinel -1
        assert record["cost_candidate"] is None
        assert record["forecast_value"] is None

    def test_ring_bounds_and_drop_accounting(self):
        ledger = DecisionLedger(capacity=8, enabled=True)
        _commit(ledger, n=5, name="first")
        _commit(ledger, n=5, name="second")
        assert ledger.records_total == 10
        assert ledger.records_dropped == 2
        records = ledger.query()
        assert len(records) == 8
        # oldest-first order survives the wrap, seq stays monotone
        seqs = [r["seq"] for r in records]
        assert seqs == sorted(seqs)
        assert [r["name"] for r in records] == (
            ["first"] * 3 + ["second"] * 5
        )

    def test_oversized_batch_keeps_newest_rows(self):
        ledger = DecisionLedger(capacity=4, enabled=True)
        batch = ledger.begin(
            "ha", 6, name=[f"r{i}" for i in range(6)]
        )
        ledger.commit(batch)
        assert [r["name"] for r in ledger.query()] == [
            "r2", "r3", "r4", "r5",
        ]
        assert ledger.records_dropped == 2

    def test_winning_stage_precedence(self):
        ledger = DecisionLedger(capacity=16, enabled=True)
        batch = ledger.begin("ha", 6, name=[
            "reactive", "raise", "clamp", "blend", "blind", "floor",
        ])
        batch.annotate(
            base_desired=np.array([3, 3, 3, 3, 3, 3], np.int32),
            final_desired=np.array([3, 5, 2, 3, 3, 3], np.int32),
            forecast_blend=np.array(
                [False, False, False, True, False, False]
            ),
            cost_blind=np.array(
                [False, False, False, False, True, False]
            ),
            solver_rung=np.array(
                ["device", "device", "device", "device", "device",
                 "floor"], object,
            ),
        )
        ledger.commit(batch)
        stages = {
            r["name"]: r["winning_stage"] for r in ledger.query()
        }
        assert stages == {
            "reactive": STAGE_REACTIVE,
            "raise": STAGE_COST_RAISE,
            "clamp": STAGE_COST_CLAMP,
            "blend": STAGE_FORECAST_BLEND,
            "blind": STAGE_COST_BLIND,
            "floor": STAGE_DEGRADED_FLOOR,
        }

    def test_deferred_rows_name_admission(self):
        ledger = DecisionLedger(capacity=8, enabled=True)
        batch = ledger.begin("tenant", 2, name=["r0", "r1"])
        batch.annotate(
            base_desired=np.array([2, 2], np.int32),
            final_desired=np.array([2, 2], np.int32),
            deferred=np.array([False, True]),
        )
        ledger.commit(batch)
        stages = [r["winning_stage"] for r in ledger.query()]
        assert stages == [STAGE_REACTIVE, STAGE_ADMISSION_DEFERRAL]

    def test_export_jsonl_is_valid_and_nan_free(self, tmp_path):
        ledger = DecisionLedger(capacity=8, enabled=True)
        _commit(ledger, n=3, name=["a", "b", "c"])
        path = str(tmp_path / "decisions.jsonl")
        assert ledger.export_jsonl(path) == 3
        lines = open(path).read().splitlines()
        assert len(lines) == 3
        for line in lines:
            record = json.loads(line)  # strict: no NaN literals
            assert record["kind"] == "ha"
            assert record["forecast_value"] is None

    def test_decisions_export_path_sibling(self):
        assert decisions_export_path("/x/trace.jsonl") == (
            "/x/trace.decisions.jsonl"
        )
        assert decisions_export_path("/x/trace") == (
            "/x/trace.decisions.jsonl"
        )

    def test_annotate_rows_composes_with_scalars(self):
        ledger = DecisionLedger(capacity=8, enabled=True)
        batch = ledger.begin("ha", 3, name=["a", "b", "c"])
        batch.annotate(solver_rung="device")
        batch.annotate_rows([2], solver_rung="floor")
        batch.annotate_rows(
            [0, 2], cost_risk=np.array([0.5, 0.0, 0.75], np.float32)
        )
        ledger.commit(batch)
        records = {r["name"]: r for r in ledger.query()}
        assert records["a"]["solver_rung"] == "device"
        assert records["c"]["solver_rung"] == "floor"
        assert records["a"]["cost_risk"] == 0.5
        assert records["b"]["cost_risk"] is None
        assert records["c"]["cost_risk"] == 0.75


# -- the off pin: byte-identical decisions, mark-free hot path ---------------


def _decision_world():
    """A seeded runtime whose every tick exercises decide + forecast +
    cost (SLO-opted HA with a forecast spec over a scripted metric):
    the full annotation surface of the ledger."""
    from karpenter_tpu.api.core import ObjectMeta
    from karpenter_tpu.api.horizontalautoscaler import (
        Behavior,
        CrossVersionObjectReference,
        ForecastSpec,
        HorizontalAutoscaler,
        HorizontalAutoscalerSpec,
        Metric,
        MetricTarget,
        PrometheusMetricSource,
        ScalingRules,
        SLOSpec,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup,
        ScalableNodeGroupSpec,
    )
    from karpenter_tpu.cloudprovider.fake import FakeFactory
    from karpenter_tpu.runtime import KarpenterRuntime, Options

    clock = {"now": 1_000_000.0}
    provider = FakeFactory()
    provider.node_replicas["g"] = 2
    runtime = KarpenterRuntime(
        Options(), cloud_provider_factory=provider,
        clock=lambda: clock["now"],
    )
    runtime.store.create(ScalableNodeGroup(
        metadata=ObjectMeta(name="g"),
        spec=ScalableNodeGroupSpec(
            replicas=2, type="FakeNodeGroup", id="g"
        ),
    ))
    runtime.store.create(HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name="g"
            ),
            min_replicas=1, max_replicas=50,
            metrics=[Metric(prometheus=PrometheusMetricSource(
                query='karpenter_queue_length{name="q"}',
                target=MetricTarget(type="AverageValue", value=4),
            ))],
            behavior=Behavior(
                scale_down=ScalingRules(
                    stabilization_window_seconds=0
                ),
                forecast=ForecastSpec(
                    horizon_seconds=30, min_samples=3, model="linear",
                ),
                slo=SLOSpec(
                    target_value=3.0, violation_cost_weight=25.0,
                ),
            ),
        ),
    ))
    gauge = runtime.registry.register("queue", "length")
    return runtime, provider, gauge, clock


def _run_world(ticks: int = 12):
    runtime, provider, gauge, clock = _decision_world()
    desired_trail = []
    try:
        for tick in range(ticks):
            gauge.set("q", "default", 8.0 + 3.0 * tick)
            runtime.manager._due = {k: 0.0 for k in runtime.manager._due}
            runtime.manager.reconcile_all()
            clock["now"] += 10.0
            desired_trail.append(provider.node_replicas["g"])
    finally:
        runtime.close()
    return desired_trail


class TestProvenanceOffPin:
    def test_off_is_byte_identical_and_mark_free(self, fresh_ledger):
        """The --provenance off posture (default): decisions are
        byte-identical with the ledger on or off, and the off path
        records nothing (mark-free hot path) — mirroring the PR 9
        tracing-off pin."""
        fresh_ledger.enabled = True
        with_ledger = _run_world()
        on_records = default_ledger().records_total
        assert on_records > 0, "enabled world must record decisions"
        on_stages = {
            r["winning_stage"] for r in default_ledger().query()
        }
        assert on_stages and on_stages <= {
            "reactive", "forecast_blend", "cost_raise", "cost_clamp",
            "cost_blind",
        }

        off = reset_default_ledger(enabled=False)
        without_ledger = _run_world()
        assert without_ledger == with_ledger, (
            "the ledger observes; it must never change a decision"
        )
        assert off.records_total == 0
        assert off.query() == []

    def test_runtime_option_enables_default_off(self, fresh_ledger):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        fresh_ledger.enabled = False
        runtime = KarpenterRuntime(
            Options(), cloud_provider_factory=FakeFactory()
        )
        try:
            assert runtime.decision_ledger.enabled is False
        finally:
            runtime.close()
        runtime = KarpenterRuntime(
            Options(provenance=True),
            cloud_provider_factory=FakeFactory(),
        )
        try:
            assert runtime.decision_ledger.enabled is True
        finally:
            runtime.close()
            fresh_ledger.enabled = True


# -- /debug/decisions end to end ---------------------------------------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, json.loads(resp.read())


class TestDebugDecisionsEndpoint:
    def test_filters_and_shape(self):
        ledger = DecisionLedger(capacity=32, enabled=True)
        batch = ledger.begin(
            "tenant", 4,
            tenant=np.array(["t1", "t1", "t2", "t2"], object),
            name=["row0", "row1", "row0", "row1"],
            group=np.array(["t1", "t1", "t2", "t2"], object),
        )
        batch.annotate(
            base_desired=np.array([1, 2, 3, 4], np.int32),
            final_desired=np.array([1, 2, 5, 4], np.int32),
        )
        ledger.commit(batch)
        server = MetricsServer(
            GaugeRegistry(), port=0, host="127.0.0.1", ledger=ledger
        )
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            status, body = _get_json(f"{base}/debug/decisions")
            assert status == 200
            assert body["enabled"] is True
            assert len(body["decisions"]) == 4
            _, t2 = _get_json(f"{base}/debug/decisions?tenant=t2")
            assert len(t2["decisions"]) == 2
            assert t2["decisions"][0]["winning_stage"] == "cost_raise"
            _, limited = _get_json(
                f"{base}/debug/decisions?kind=tenant&limit=1"
            )
            assert len(limited["decisions"]) == 1
            _, nothing = _get_json(
                f"{base}/debug/decisions?group=missing"
            )
            assert nothing["decisions"] == []
        finally:
            server.stop()


# -- the multi-tenant acceptance replay --------------------------------------


class TestMultitenantProvenanceAcceptance:
    def test_pinned_tick_names_stage_rung_and_trace(
        self, tmp_path, fresh_ledger
    ):
        """ISSUE 12 acceptance: in a seeded --simulate --cost
        --multitenant replay, the pinned tick's ledger records name the
        winning stage, the solver rung used, and a trace id that
        resolves in the exported trace JSONL."""
        from karpenter_tpu.observability import (
            reset_default_tracer,
            set_default_tracer,
        )
        from karpenter_tpu.observability.tracing import default_tracer
        from karpenter_tpu.simulate import simulate_multitenant

        saved_tracer = default_tracer()
        reset_default_tracer()
        trace_path = str(tmp_path / "trace.jsonl")
        try:
            report = simulate_multitenant(
                tenants=4, ticks=6, provenance=True,
                trace_export=trace_path,
            )
        finally:
            set_default_tracer(saved_tracer)
        prov = report["provenance"]
        assert prov["records"] == 4 * 4 * 6  # tenants x rows x ticks
        pinned = prov["pinned"]
        assert len(pinned) == 4 * 4
        for row in pinned:
            assert row["why"] in (
                "reactive", "cost_raise", "cost_clamp",
                "forecast_blend", "admission_deferral", "cost_blind",
                "degraded_floor",
            )
            assert row["rung"] in (
                "device", "isolated", "mirror", "floor", "sidecar",
                "numpy",
            )
            assert row["trace"], "pinned records must backlink a trace"
        # cost refinement must actually have explained at least one
        # count (the seeded demand guarantees SLO raises)
        assert prov["by_stage"].get("cost_raise", 0) > 0
        # the trace ids RESOLVE in the exported Chrome-trace JSONL
        exported_traces = set()
        with open(trace_path) as fh:
            for line in fh:
                event = json.loads(line)
                if event.get("ph") == "X":
                    exported_traces.add(event["cat"])
        assert {row["trace"] for row in pinned} <= exported_traces
        # and the decision JSONL landed NEXT TO the trace export
        decisions_path = report["decisions_export"]
        assert decisions_path == decisions_export_path(trace_path)
        records = [
            json.loads(line) for line in open(decisions_path)
        ]
        assert len(records) == report["decision_records"]
        assert {r["tenant"] for r in records} == {
            "t0000", "t0001", "t0002", "t0003",
        }


# -- structural overhead guard -----------------------------------------------


class TestProvenanceOverheadGuard:
    def test_enabled_vs_disabled_tick_overhead(self, fresh_ledger):
        """The wall-clock guard with generous flake headroom: `make
        bench-provenance` publishes the honest <=5% number
        (docs/BENCHMARKS.md); this pin catches gross regressions."""
        import time

        import numpy as _np

        def run(enabled: bool, ticks: int = 12):
            fresh_ledger.enabled = enabled
            runtime, provider, gauge, clock = _decision_world()
            times = []
            try:
                for tick in range(4):
                    gauge.set("q", "default", 8.0 + tick)
                    runtime.manager.converge(1)
                    clock["now"] += 10.0
                for tick in range(ticks):
                    gauge.set("q", "default", 8.0 + tick)
                    runtime.manager._due = {
                        k: 0.0 for k in runtime.manager._due
                    }
                    t0 = time.perf_counter()
                    runtime.manager.reconcile_all()
                    times.append(time.perf_counter() - t0)
                    clock["now"] += 10.0
            finally:
                runtime.close()
            return float(_np.percentile(times, 50))

        off = run(False)
        on = run(True)
        assert on <= off * 1.75 + 0.002, (
            f"provenance overhead p50 {off * 1e3:.3f}ms -> "
            f"{on * 1e3:.3f}ms"
        )
