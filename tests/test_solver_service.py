"""Shared solve service (karpenter_tpu/solver): coalescing, shape-bucketed
compile cache, backpressure/deadlines, numpy fallback, metrics surface,
and the public pendingcapacity encoding API it rides with.

The acceptance pin: 8 concurrent same-bucket requests produce at most 2
device dispatches; a post-warmup stream of jittered pod counts within one
bucket causes zero recompiles (per the service's compile-cache counters);
and every service result is element-for-element identical to a direct
ops/binpack call.
"""

import threading
import time

import numpy as np
import pytest

from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.ops import binpack as B
from karpenter_tpu.ops.numpy_binpack import binpack_numpy
from karpenter_tpu.solver import (
    SolverSaturated,
    SolverService,
    SolverTimeout,
    bucket_up,
)


def make_inputs(pods, types, seed=0, weighted=False, constrained=False):
    """Integer-valued requests: every float reduction in the solve is then
    exact, so equality assertions are bitwise, not approximate."""
    rng = np.random.default_rng(seed)
    req = np.stack(
        [
            rng.integers(1, 8, pods),
            rng.integers(1, 32, pods),
            np.ones(pods),
        ],
        axis=1,
    ).astype(np.float32)
    alloc = np.stack(
        [
            rng.choice([8, 16, 32, 64], types),
            rng.choice([32, 64, 128], types),
            np.full(types, 110.0),
        ],
        axis=1,
    ).astype(np.float32)
    kwargs = {}
    if weighted:
        kwargs["pod_weight"] = rng.integers(1, 5, pods).astype(np.int32)
    if constrained:
        kwargs["pod_group_forbidden"] = rng.random((pods, types)) < 0.2
        kwargs["pod_group_score"] = rng.integers(
            0, 3, (pods, types)
        ).astype(np.float32)
        kwargs["pod_exclusive"] = rng.random(pods) < 0.1
    return B.BinPackInputs(
        pod_requests=req,
        pod_valid=np.ones(pods, bool),
        pod_intolerant=rng.random((pods, 16)) < 0.05,
        pod_required=rng.random((pods, 16)) < 0.03,
        group_allocatable=alloc,
        group_taints=rng.random((types, 16)) < 0.1,
        group_labels=rng.random((types, 16)) < 0.8,
        **kwargs,
    )


def assert_outputs_equal(got, want):
    for name in ("assigned", "assigned_count", "nodes_needed", "lp_bound"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(want, name)),
            err_msg=name,
        )
    assert int(got.unschedulable) == int(want.unschedulable)


@pytest.fixture
def service():
    svc = SolverService(
        registry=GaugeRegistry(), window_s=0.05, max_batch=8
    )
    yield svc
    svc.close()


class TestBucketLadder:
    def test_bucket_up_rungs(self):
        assert bucket_up(1, 256) == 256
        assert bucket_up(256, 256) == 256
        assert bucket_up(257, 256) == 384
        assert bucket_up(385, 256) == 512
        assert bucket_up(513, 256) == 768
        assert bucket_up(1000, 256) == 1024
        # consecutive rungs <= 1.5x apart: padding waste bounded
        rungs = sorted({bucket_up(n, 8) for n in range(1, 4096)})
        for a, b in zip(rungs, rungs[1:]):
            assert b <= a * 1.5 + 1e-9

    def test_padding_is_identity_at_bucket_shape(self):
        from karpenter_tpu.solver import bucket_shape, pad_to_bucket

        inputs = make_inputs(256, 8)
        # 16-wide taint/label universes pad up to their floors, so
        # build one already at floor widths to check identity
        padded_once = pad_to_bucket(inputs, bucket_shape(inputs))
        again = pad_to_bucket(padded_once, bucket_shape(padded_once))
        assert again is padded_once


class TestAcceptance:
    def test_coalescing_cache_stability_and_bitwise_identity(self, service):
        """The ISSUE acceptance pin, in one flow."""
        inputs = [make_inputs(100 + i, 5, seed=i) for i in range(8)]

        # warm the two batch sizes this test will see (batch=8 coalesced,
        # batch=1 sequential) so the streaming phase measures steady state
        service.solve(make_inputs(90, 5, seed=99), backend="xla")

        results = [None] * 8
        barrier = threading.Barrier(8)

        def submit(i):
            barrier.wait()
            results[i] = service.solve(inputs[i], backend="xla")

        dispatches_before = service.stats.dispatches
        threads = [
            threading.Thread(target=submit, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        # 8 concurrent same-bucket requests -> at most 2 device dispatches
        assert service.stats.dispatches - dispatches_before <= 2
        assert service.stats.last_coalesce_factor >= 4

        # results identical to direct ops/binpack calls
        for i in range(8):
            assert_outputs_equal(
                results[i], B.solve(inputs[i], backend="xla")
            )

        # post-warmup stream of jittered pod counts within one bucket:
        # ZERO recompiles (the batch=1 and batch<=8 programs are warm)
        misses_before = service.stats.compile_cache_misses
        for pods in (70, 110, 200, 255, 130, 64, 256):
            out = service.solve(
                make_inputs(pods, 5, seed=pods), backend="xla"
            )
            assert out.assigned.shape == (pods,)
        assert service.stats.compile_cache_misses == misses_before
        assert service.stats.compile_cache_hits > 0


class TestEquality:
    @pytest.mark.parametrize("weighted", [False, True])
    @pytest.mark.parametrize("constrained", [False, True])
    def test_service_matches_direct_across_operand_shapes(
        self, service, weighted, constrained
    ):
        for pods, types in ((17, 3), (256, 8), (300, 12)):
            inputs = make_inputs(
                pods, types, seed=pods,
                weighted=weighted, constrained=constrained,
            )
            assert_outputs_equal(
                service.solve(inputs, backend="xla"),
                B.solve(inputs, backend="xla"),
            )

    def test_numpy_backend_matches_direct(self, service):
        inputs = make_inputs(40, 4, seed=7)
        assert_outputs_equal(
            service.solve(inputs, backend="numpy"),
            binpack_numpy(inputs, buckets=32),
        )
        # the host program never touches the device path
        assert service.stats.dispatches == 0

    def test_distinct_buckets_solve_independently(self, service):
        """Requests in different shape buckets coalesce into separate
        device calls but all complete correctly."""
        small = make_inputs(50, 4, seed=1)
        large = make_inputs(300, 4, seed=2)
        results = {}
        barrier = threading.Barrier(2)

        def submit(name, inputs):
            barrier.wait()
            results[name] = service.solve(inputs, backend="xla")

        threads = [
            threading.Thread(target=submit, args=("small", small)),
            threading.Thread(target=submit, args=("large", large)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert_outputs_equal(results["small"], B.solve(small, backend="xla"))
        assert_outputs_equal(results["large"], B.solve(large, backend="xla"))


class TestBackpressureAndDeadlines:
    def test_deadline_expiry_raises_when_configured(self):
        release = threading.Event()

        def stuck_device(inputs, buckets=32, backend="auto"):
            release.wait(5.0)
            return binpack_numpy(inputs, buckets=buckets)

        svc = SolverService(
            registry=GaugeRegistry(),
            device_solver=stuck_device,
            on_timeout="raise",
            window_s=0.0,
        )
        try:
            with pytest.raises(SolverTimeout):
                svc.solve(make_inputs(20, 3), timeout=0.05)
            assert svc.stats.deadline_expired == 1
        finally:
            release.set()
            svc.close()

    def test_deadline_expiry_falls_back_to_numpy_by_default(self):
        release = threading.Event()

        def stuck_device(inputs, buckets=32, backend="auto"):
            release.wait(5.0)
            return binpack_numpy(inputs, buckets=buckets)

        svc = SolverService(
            registry=GaugeRegistry(),
            device_solver=stuck_device,
            window_s=0.0,
        )
        try:
            inputs = make_inputs(20, 3)
            out = svc.solve(inputs, timeout=0.05)
            assert_outputs_equal(out, binpack_numpy(inputs, buckets=32))
            assert svc.stats.deadline_expired == 1
            assert svc.stats.fallbacks == 1
        finally:
            release.set()
            svc.close()

    def test_device_failure_falls_back_to_numpy(self):
        def broken_device(inputs, buckets=32, backend="auto"):
            raise RuntimeError("injected device failure")

        svc = SolverService(
            registry=GaugeRegistry(), device_solver=broken_device,
            window_s=0.0,
        )
        try:
            inputs = make_inputs(30, 4, seed=3)
            out = svc.solve(inputs)
            assert_outputs_equal(out, binpack_numpy(inputs, buckets=32))
            assert svc.stats.fallbacks == 1
        finally:
            svc.close()

    def test_saturated_queue_degrades_inline(self):
        """A full bounded queue must answer the overflow request from the
        numpy backend instead of queueing without bound."""
        release = threading.Event()
        started = threading.Event()

        def slow_device(inputs, buckets=32, backend="auto"):
            started.set()
            release.wait(5.0)
            return binpack_numpy(inputs, buckets=buckets)

        svc = SolverService(
            registry=GaugeRegistry(),
            device_solver=slow_device,
            max_queue=1,
            window_s=0.0,
        )
        try:
            # occupy the worker, then fill the single queue slot
            blocked = svc.submit(make_inputs(10, 2, seed=1))
            assert started.wait(2.0)
            svc.submit(make_inputs(10, 2, seed=2))
            with pytest.raises(SolverSaturated):
                svc.submit(make_inputs(10, 2, seed=3))
            # solve() turns saturation into the inline numpy answer
            inputs = make_inputs(10, 2, seed=4)
            out = svc.solve(inputs)
            assert_outputs_equal(out, binpack_numpy(inputs, buckets=32))
            assert svc.stats.rejected == 2
            assert svc.stats.fallbacks == 1
            release.set()
            blocked.result(5.0)
        finally:
            release.set()
            svc.close()


class TestMetricsSurface:
    def test_gauges_registered_and_published(self):
        registry = GaugeRegistry()
        svc = SolverService(registry=registry, window_s=0.0)
        try:
            svc.solve(make_inputs(20, 3), backend="xla")
            svc.publish_gauges()
            text = registry.expose_text()
            for series in (
                "karpenter_solver_queue_depth",
                "karpenter_solver_coalesce_factor",
                "karpenter_solver_requests_total",
                "karpenter_solver_dispatch_total",
                "karpenter_solver_compile_cache_misses_total",
                "karpenter_solver_stage_p50_ms",
                "karpenter_solver_window_ms",
                "karpenter_solver_pipeline_depth",
            ):
                assert series in text, series
        finally:
            svc.close()

    def test_manager_publishes_service_gauges_each_tick(self):
        """The satellite fix: /metrics shows queue depth + coalesce
        factor through the Manager with no extra wiring in __main__."""
        from karpenter_tpu.controllers import Manager
        from karpenter_tpu.store import Store

        registry = GaugeRegistry()
        svc = SolverService(registry=registry, window_s=0.0)
        try:
            manager = Manager(
                Store(), registry=registry, solver_service=svc
            )
            manager.reconcile_all()
            gauge = registry.gauge("solver", "queue_depth")
            assert gauge.get("-", "-") == 0.0
        finally:
            svc.close()

    def test_runtime_wires_all_callers_through_service(self):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        rt = KarpenterRuntime(
            Options(cloud_provider="fake"),
            cloud_provider_factory=FakeFactory(),
        )
        try:
            assert rt.producer_factory.solver == rt.solver_service.solve
            assert rt.batch_autoscaler.decider == rt.solver_service.decide
            # the service's gauges live in the runtime registry the
            # MetricsServer serves
            assert rt.solver_service.registry is rt.registry
        finally:
            rt.close()

    def test_decide_routes_and_counts(self):
        from karpenter_tpu.ops.decision import decide_jit
        from karpenter_tpu.parallel.mesh import example_decision_inputs

        svc = SolverService(registry=GaugeRegistry())
        try:
            inputs = example_decision_inputs(N=4, M=2, seed=0)
            out = svc.decide(inputs)
            want = decide_jit(inputs)
            np.testing.assert_array_equal(
                np.asarray(out.desired), np.asarray(want.desired)
            )
            assert svc.stats.decide_calls == 1
        finally:
            svc.close()


class TestPublicEncodingAPI:
    def test_encode_snapshot_matches_encoder_module(self):
        from karpenter_tpu.metrics.producers import pendingcapacity as PC
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            encoder,
        )
        from karpenter_tpu.store.columnar import snapshot_from_pods

        snap = snapshot_from_pods([])
        profiles = [({"cpu": 8.0, "pods": 110.0}, set(), set())]
        public = PC.encode_snapshot(snap, profiles)
        private = encoder._encode_full(snap, profiles)
        np.testing.assert_array_equal(
            public.group_allocatable, private.group_allocatable
        )

    def test_group_profile_public_name(self):
        from karpenter_tpu.metrics.producers import pendingcapacity as PC

        assert PC.group_profile([], {}) == ({}, set(), set())

    def test_underscore_shims_are_gone(self):
        """The deprecated PR-1 compat shims were removed: the package no
        longer re-exports the private helpers (their home submodules do
        — encoder, partition, spread, anti, exclusion)."""
        import importlib

        module = importlib.import_module(
            "karpenter_tpu.metrics.producers.pendingcapacity"
        )
        for name in (
            "_group_profile",
            "_encode_from_cache",
            "_dedup_rows",
            "_group_arrays",
            "_water_fill",
            "_expand_spread_rows",
            "_expand_anti_rows",
        ):
            with pytest.raises(AttributeError):
                getattr(module, name)

    def test_encode_snapshot_honors_patched_seam(self, monkeypatch):
        """Internal solve paths resolve `encode_snapshot` through the
        package namespace at call time, so patching it intercepts every
        encode (the seam the encode-counting tests rely on)."""
        from karpenter_tpu.metrics.producers import pendingcapacity as PC
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.store import Store

        calls = []
        real = PC.encode_snapshot

        def counting(*args, **kwargs):
            calls.append(1)
            return real(*args, **kwargs)

        monkeypatch.setattr(PC, "encode_snapshot", counting)
        store = Store()
        from karpenter_tpu.api.core import (
            Container,
            ObjectMeta,
            Pod,
            PodSpec,
        )
        from karpenter_tpu.api.metricsproducer import (
            MetricsProducer,
            MetricsProducerSpec,
            PendingCapacitySpec,
        )
        from karpenter_tpu.utils.quantity import Quantity

        store.create(
            MetricsProducer(
                metadata=ObjectMeta(name="mp"),
                spec=MetricsProducerSpec(
                    pending_capacity=PendingCapacitySpec(
                        node_selector={"g": "a"}
                    )
                ),
            )
        )
        store.create(
            Pod(
                metadata=ObjectMeta(name="p0"),
                spec=PodSpec(
                    containers=[
                        Container(requests={"cpu": Quantity.parse("1")})
                    ]
                ),
            )
        )
        mps = store.list("MetricsProducer")
        PC.solve_pending(store, mps, GaugeRegistry())
        assert calls == [1]


class TestCoalesceTiming:
    def test_fixed_window_holds_for_stragglers(self):
        """adaptive_window=False pins the pre-overhaul fixed window: a
        submit landing inside it joins the open batch."""
        svc = SolverService(
            registry=GaugeRegistry(), window_s=0.2, max_batch=4,
            adaptive_window=False,
        )
        try:
            results = {}

            def submit(name, delay):
                time.sleep(delay)
                results[name] = svc.solve(
                    make_inputs(25, 3, seed=len(name)), backend="xla"
                )

            threads = [
                threading.Thread(target=submit, args=("a", 0.0)),
                threading.Thread(target=submit, args=("b", 0.05)),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(results) == 2
            assert svc.stats.dispatches == 1
            assert svc.stats.last_coalesce_factor == 2
        finally:
            svc.close()

    def test_adaptive_idle_queue_skips_the_window(self):
        """The tentpole fix: a lone request on an idle queue must NOT
        wait out the batching timer. With a punitive 0.5 s max window,
        sequential solves complete in far less than one window each."""
        svc = SolverService(
            registry=GaugeRegistry(), window_s=0.5, max_batch=8
        )
        try:
            inputs = make_inputs(40, 4, seed=1)
            svc.solve(inputs, backend="xla")  # warm the compile
            t0 = time.perf_counter()
            for _ in range(3):
                svc.solve(inputs, backend="xla")
            elapsed = time.perf_counter() - t0
            assert elapsed < 0.5, (
                f"3 idle-queue solves took {elapsed:.3f}s — the fixed "
                "window is back"
            )
            assert svc.stats.immediate_dispatches >= 3
        finally:
            svc.close()

    def test_adaptive_window_widens_under_concurrency(self):
        """Concurrent submitters must still coalesce (the acceptance
        criterion: coalesce factor >= 4 under concurrency >= 4) even
        with the adaptive window dispatching idle traffic immediately."""
        svc = SolverService(
            registry=GaugeRegistry(), window_s=0.05, max_batch=8
        )
        try:
            inputs = [make_inputs(60 + i, 4, seed=i) for i in range(8)]
            svc.solve(make_inputs(50, 4, seed=99), backend="xla")  # warm
            results = [None] * 8
            barrier = threading.Barrier(8)

            def submit(i):
                barrier.wait()
                results[i] = svc.solve(inputs[i], backend="xla")

            threads = [
                threading.Thread(target=submit, args=(i,))
                for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(r is not None for r in results)
            assert svc.stats.last_coalesce_factor >= 4
        finally:
            svc.close()


class TestPipelinedDispatch:
    def test_sustained_load_overlaps_dispatches(self):
        """With max_batch capping each dispatch, a burst larger than one
        batch must pipeline: at least one dispatch is issued while the
        previous one is still in flight — and every result stays
        bitwise-correct."""
        svc = SolverService(
            registry=GaugeRegistry(), window_s=0.02, max_batch=2,
            pipeline_depth=1,
        )
        try:
            inputs = [make_inputs(30 + i, 3, seed=i) for i in range(6)]
            svc.solve(inputs[0], backend="xla")  # warm batch=1
            futures = [
                svc.submit(inp, backend="xla") for inp in inputs
            ]
            results = [f.result(30.0) for f in futures]
            for inp, out in zip(inputs, results):
                assert_outputs_equal(out, B.solve(inp, backend="xla"))
            assert svc.stats.pipeline_overlaps >= 1
        finally:
            svc.close()

    def test_closed_loop_concurrency_overlaps_dispatches(self):
        """The PR 8 satellite pin: CLOSED-LOOP concurrent callers (the
        bench-hotpath shape that used to publish pipeline_overlaps: 0)
        must record >= 1 overlap. The fix: a lone coalesced batch with
        nothing in flight splits into pipeline chunks, so chunk k+1's
        dispatch overlaps chunk k's compute — and every result stays
        bitwise-correct."""
        svc = SolverService(
            registry=GaugeRegistry(), window_s=0.2, max_batch=8,
            adaptive_window=False, pipeline_depth=1,
        )
        try:
            inputs = [make_inputs(30 + i, 3, seed=i) for i in range(8)]
            svc.solve(inputs[0], backend="xla")  # warm
            results = [None] * 8
            barrier = threading.Barrier(8)

            def submit(i):
                barrier.wait()
                results[i] = svc.solve(inputs[i], backend="xla")

            threads = [
                threading.Thread(target=submit, args=(i,))
                for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for inp, out in zip(inputs, results):
                assert_outputs_equal(out, B.solve(inp, backend="xla"))
            assert svc.stats.pipeline_splits >= 1
            assert svc.stats.pipeline_overlaps >= 1
        finally:
            svc.close()

    def test_small_batches_never_split(self):
        """The coalescing contract: batches below the split floor keep
        riding ONE dispatch (the fixed-window straggler test above pins
        dispatches == 1 for a pair — this pins the boundary)."""
        svc = SolverService(
            registry=GaugeRegistry(), window_s=0.2, max_batch=8,
            adaptive_window=False, pipeline_depth=1,
        )
        try:
            inputs = [make_inputs(25 + i, 3, seed=i) for i in range(3)]
            svc.solve(inputs[0], backend="xla")  # warm
            dispatches = svc.stats.dispatches
            results = [None] * 3
            barrier = threading.Barrier(3)

            def submit(i):
                barrier.wait()
                results[i] = svc.solve(inputs[i], backend="xla")

            threads = [
                threading.Thread(target=submit, args=(i,))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert svc.stats.dispatches - dispatches == 1
            assert svc.stats.pipeline_splits == 0
            for inp, out in zip(inputs, results):
                assert_outputs_equal(out, B.solve(inp, backend="xla"))
        finally:
            svc.close()

    def test_pipeline_depth_zero_is_serial(self):
        svc = SolverService(
            registry=GaugeRegistry(), window_s=0.0, pipeline_depth=0
        )
        try:
            inputs = make_inputs(20, 3, seed=5)
            assert_outputs_equal(
                svc.solve(inputs, backend="xla"),
                B.solve(inputs, backend="xla"),
            )
            assert svc.stats.pipeline_overlaps == 0
        finally:
            svc.close()

    def test_inflight_device_failure_degrades_to_numpy(self):
        """An async dispatch whose failure surfaces at drain time (not
        dispatch time) must still answer every request from numpy."""
        svc = SolverService(registry=GaugeRegistry(), window_s=0.0)
        try:
            import dataclasses

            calls = {"n": 0}
            real = svc._compiled_for

            def exploding(cache_key, donate=False):
                fn, fresh = real(cache_key, donate=donate)

                def wrapped(stacked, buckets):
                    calls["n"] += 1
                    out = fn(stacked, buckets)
                    # poison the result so the block_until_ready in the
                    # drain path raises (async-failure analog)
                    return dataclasses.replace(
                        out, assigned=_Exploding()
                    )

                return wrapped, fresh

            class _Exploding:
                def block_until_ready(self):
                    raise RuntimeError("injected in-flight failure")

                @property
                def shape(self):
                    return (0,)

            svc._compiled_for = exploding
            inputs = make_inputs(15, 3, seed=9)
            out = svc.solve(inputs, backend="xla")
            assert_outputs_equal(out, binpack_numpy(inputs, buckets=32))
            assert svc.stats.fallbacks == 1
            assert calls["n"] == 1
        finally:
            svc.close()


class TestDonationParity:
    def test_donating_compile_matches_non_donating(self):
        """The donation-backed program family must produce outputs
        bitwise-identical to the non-donating family on the same
        stacked operands (donation changes buffer lifetime, never
        values) — compiled explicitly on BOTH families regardless of
        whether this backend supports donation."""
        import warnings

        import jax

        from karpenter_tpu.solver.bucketing import (
            bucket_shape,
            pad_to_bucket,
        )
        from karpenter_tpu.solver.service import _stack_inputs

        svc = SolverService(registry=GaugeRegistry(), window_s=0.0)
        try:
            inputs = make_inputs(40, 4, seed=11)
            shape = bucket_shape(inputs)
            padded = pad_to_bucket(inputs, shape)
            key = ("xla", shape, 1, 32, (False, False, False, False),
                   "map")
            keep, _ = svc._compiled_for(key, donate=False)
            donate, _ = svc._compiled_for(key, donate=True)
            out_keep = jax.device_get(
                keep(jax.device_put(_stack_inputs([padded])), 32)
            )
            with warnings.catch_warnings():
                # on CPU donation is a no-op and jax says so per
                # executable; this test compiles the donating family
                # here deliberately
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable",
                )
                out_donate = jax.device_get(
                    donate(jax.device_put(_stack_inputs([padded])), 32)
                )
            for name in (
                "assigned", "assigned_count", "nodes_needed",
                "lp_bound", "unschedulable",
            ):
                np.testing.assert_array_equal(
                    np.asarray(getattr(out_donate, name)),
                    np.asarray(getattr(out_keep, name)),
                    err_msg=name,
                )
            # two distinct compile-cache families, no aliasing
            assert svc.stats.compile_cache_misses == 2
        finally:
            svc.close()


class TestLatencyRegressionGuard:
    def test_idle_service_p50_within_2x_of_direct(self):
        """The coalescing tax must not return: on an idle queue the
        service path stays within 2x of a direct ops/binpack call on a
        small fixed workload (the non-slow canary for the bench-hotpath
        acceptance ratio)."""
        inputs = make_inputs(256, 8, seed=42)
        iters = 15

        def p50(fn):
            times = []
            for _ in range(iters):
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
            return float(np.percentile(times, 50))

        import jax

        def direct():
            jax.block_until_ready(B.solve(inputs, backend="xla"))

        direct()  # warm
        direct_p50 = p50(direct)

        svc = SolverService(registry=GaugeRegistry(), max_batch=8)
        try:
            svc.solve(inputs, backend="xla")  # warm
            service_p50 = p50(
                lambda: svc.solve(inputs, backend="xla")
            )
        finally:
            svc.close()
        # generous absolute floor: at sub-millisecond direct solves the
        # thread handoff dominates and the RATIO is meaningless noise
        assert service_p50 <= max(2 * direct_p50, 0.01), (
            f"idle service p50 {service_p50 * 1e3:.2f}ms vs direct "
            f"{direct_p50 * 1e3:.2f}ms — coalescing tax is back"
        )


class TestShardedDispatch:
    """The PR 8 tentpole (docs/solver-service.md "Sharded dispatch"):
    above the cell threshold a request routes through the pods x groups
    mesh behind the SAME service seam, bit-identical to the
    single-device program, degrading shard -> single-device -> numpy."""

    def test_above_threshold_routes_through_mesh_with_parity(self):
        svc = SolverService(registry=GaugeRegistry(), shard_threshold=1)
        try:
            inputs = make_inputs(333, 13, seed=3, weighted=True,
                                 constrained=True)
            out = svc.solve(inputs, backend="xla")
            assert svc.stats.shard_requests == 1
            assert svc.stats.shard_dispatches == 1
            assert svc.stats.fallbacks == 0
            assert_outputs_equal(out, B.solve(inputs, backend="xla"))
            assert_outputs_equal(out, binpack_numpy(inputs, buckets=32))
            # the sharded route is visible on the latency surface too
            assert "upload" in svc.stage_percentiles()
        finally:
            svc.close()

    def test_below_threshold_stays_single_device(self):
        svc = SolverService(
            registry=GaugeRegistry(), shard_threshold=10**9
        )
        try:
            inputs = make_inputs(64, 4, seed=1)
            out = svc.solve(inputs, backend="xla")
            assert svc.stats.shard_requests == 0
            assert svc.stats.shard_dispatches == 0
            assert_outputs_equal(out, B.solve(inputs, backend="xla"))
        finally:
            svc.close()

    def test_threshold_zero_disables_sharding(self):
        svc = SolverService(registry=GaugeRegistry(), shard_threshold=0)
        try:
            svc.solve(make_inputs(300, 12, seed=2), backend="xla")
            assert svc.stats.shard_dispatches == 0
            assert svc._shard_mesh() is None
        finally:
            svc.close()

    def test_single_device_mesh_shapes_stay_unsharded(self):
        """An explicit 1x1 --shard-mesh (or a 1-device cap) must NOT
        build a mesh: routing above-threshold traffic through the
        inline sharded path with zero parallelism gain while reporting
        sharding active is strictly worse than the single-device
        program."""
        for kwargs in (
            {"shard_mesh_shape": (1, 1)},
            {"shard_devices": 1},
        ):
            svc = SolverService(
                registry=GaugeRegistry(), shard_threshold=1, **kwargs
            )
            try:
                inputs = make_inputs(128, 6, seed=6)
                out = svc.solve(inputs, backend="xla")
                assert svc._shard_mesh() is None, kwargs
                assert svc.stats.shard_dispatches == 0, kwargs
                assert_outputs_equal(out, B.solve(inputs, backend="xla"))
            finally:
                svc.close()

    def test_shard_failure_degrades_to_single_device_then_sticks(self):
        """The ladder: a shard-path failure re-runs the SAME batch on
        the single-device program (answered on device, NOT from numpy)
        and stops routing new traffic to the mesh; reset_caches — the
        recovery-boot seam — re-arms it."""
        svc = SolverService(registry=GaugeRegistry(), shard_threshold=1)

        def explode(*_a, **_k):
            raise RuntimeError("injected shard failure")

        svc._sharded_xla = explode
        try:
            inputs = make_inputs(200, 9, seed=4)
            out = svc.solve(inputs, backend="xla")
            assert_outputs_equal(out, B.solve(inputs, backend="xla"))
            assert svc.stats.shard_fallbacks == 1
            assert svc.stats.fallbacks == 0  # device answered, not numpy
            assert svc._shard_broken
            # subsequent traffic routes single-device straight away
            out2 = svc.solve(inputs, backend="xla")
            assert_outputs_equal(out2, B.solve(inputs, backend="xla"))
            assert svc.stats.shard_fallbacks == 1
            svc.reset_caches()
            assert not svc._shard_broken
        finally:
            svc.close()

    def test_shard_and_single_device_compile_families_never_alias(self):
        """Shard-count is part of the bucket key: the same bucket shape
        compiled sharded and unsharded must be two cache entries."""
        svc = SolverService(registry=GaugeRegistry(), shard_threshold=1)
        try:
            inputs = make_inputs(128, 6, seed=8)
            svc.solve(inputs, backend="xla")
            misses_sharded = svc.stats.compile_cache_misses
            assert misses_sharded >= 1
            svc.shard_threshold = 10**12  # same shapes, unsharded now
            svc.solve(inputs, backend="xla")
            assert svc.stats.compile_cache_misses == misses_sharded + 1
            # and a REPEAT on each route hits its own program
            hits = svc.stats.compile_cache_hits
            svc.solve(inputs, backend="xla")
            svc.shard_threshold = 1
            svc.solve(inputs, backend="xla")
            assert svc.stats.compile_cache_hits == hits + 2
            assert svc.stats.compile_cache_misses == misses_sharded + 1
        finally:
            svc.close()

    def test_consolidate_routes_through_mesh_with_parity(self):
        svc = SolverService(registry=GaugeRegistry(), shard_threshold=1)
        try:
            inputs_list = [
                make_inputs(96, 8, seed=10 + i) for i in range(4)
            ]
            results = svc.consolidate(inputs_list, backend="xla")
            assert svc.stats.shard_dispatches >= 1
            assert svc.stats.fallbacks == 0
            for inputs, out in zip(inputs_list, results):
                assert_outputs_equal(
                    out, B.solve(inputs, backend="xla")
                )
        finally:
            svc.close()

    def test_forecast_and_preempt_never_shard(self):
        """This PR pins the forecast/preempt seams to the single-device
        path: their kernels carry no sharded parity pin, so no request
        of theirs may acquire a shard key even with the threshold
        floored."""
        from karpenter_tpu.forecast.models import ForecastInputs

        svc = SolverService(registry=GaugeRegistry(), shard_threshold=1)
        try:
            rng = np.random.default_rng(0)
            S, T = 6, 16
            svc.forecast(
                ForecastInputs(
                    values=rng.uniform(0, 10, (S, T)).astype(np.float32),
                    valid=np.ones((S, T), bool),
                    times=np.tile(
                        (np.arange(T, dtype=np.float32) - (T - 1)) * 10,
                        (S, 1),
                    ),
                    weights=np.ones((S, T), np.float32),
                    horizon=np.full(S, 30.0, np.float32),
                    step_s=np.full(S, 10.0, np.float32),
                    model=np.zeros(S, np.int32),
                    season=np.zeros(S, np.int32),
                    alpha=np.full(S, 0.5, np.float32),
                    beta=np.full(S, 0.2, np.float32),
                    gamma=np.full(S, 0.2, np.float32),
                )
            )
            assert svc.stats.shard_dispatches == 0
            assert svc.stats.shard_requests == 0
        finally:
            svc.close()


class TestUploadStage:
    def test_upload_stage_and_gauge_recorded(self):
        """The satellite: host->device transfer isolated as its own
        stage (the measured baseline ROADMAP item 4's device-resident
        state attacks) and published as karpenter_solver_upload_ms."""
        registry = GaugeRegistry()
        svc = SolverService(registry=registry)
        try:
            svc.solve(make_inputs(64, 4, seed=3), backend="xla")
            stages = svc.stage_percentiles()
            assert "upload" in stages
            assert stages["upload"]["n"] >= 1
            svc.publish_gauges()
            text = registry.expose_text()
            assert "karpenter_solver_upload_ms" in text
        finally:
            svc.close()


class TestCompilePrewarm:
    """Boot-time compile pre-warm (ISSUE 14 satellite,
    docs/solver-service.md "Compile pre-warm"): one tiny real dispatch
    per always-on family through the normal queue, counted in the
    prewarm gauges, skipped once warmed, re-armed by reset_caches, and
    never able to block boot."""

    def test_warms_both_families_and_skips_on_rewarm(self):
        registry = GaugeRegistry()
        service = SolverService(registry=registry, backend="xla")
        try:
            report = service.prewarm()
            assert set(report) == {"solve", "decide"}
            for family in ("solve", "decide"):
                assert report[family]["skipped"] is False
                assert report[family]["ms"] >= 0.0
                assert registry.gauge(
                    "solver", "prewarm_compiles_total"
                ).get(family, "-") == 1.0
                assert registry.gauge(
                    "solver", "prewarm_ms"
                ).get(family, "-") is not None
            # the solve family rides the queue's compile counters: a
            # cold service's warm-up IS a fresh compile there; decide
            # rides jax.jit's own cache, so the report must NOT claim
            # a (meaningless) zero for it
            assert report["solve"]["fresh_compiles"] >= 1
            assert "fresh_compiles" not in report["decide"]

            again = service.prewarm()
            assert again == {
                "solve": {"skipped": True},
                "decide": {"skipped": True},
            }
            assert registry.gauge(
                "solver", "prewarm_compiles_total"
            ).get("solve", "-") == 1.0, "a skip must not re-count"
        finally:
            service.close()

    def test_reset_caches_rearms_the_warmup(self):
        service = SolverService(registry=GaugeRegistry(), backend="xla")
        try:
            service.prewarm()
            service.reset_caches()  # the recovery-boot seam
            report = service.prewarm(families=("solve",))
            assert report["solve"]["skipped"] is False, (
                "a reset plane must be able to re-warm"
            )
        finally:
            service.close()

    def test_unknown_family_degrades_never_raises(self):
        service = SolverService(registry=GaugeRegistry())
        try:
            report = service.prewarm(families=("solve", "nope"))
            assert report["nope"] == {
                "skipped": False, "error": "ValueError",
            }
            assert report["solve"]["skipped"] is False, (
                "one family's failure must not stop the rest"
            )
            # a failed family is retryable (not marked warmed)
            assert "nope" not in service._prewarmed
        finally:
            service.close()

    def test_runtime_wires_prewarm_compile_option(self):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        runtime = KarpenterRuntime(
            Options(prewarm_compile=True),
            cloud_provider_factory=FakeFactory(),
        )
        try:
            gauge = runtime.registry.gauge(
                "solver", "prewarm_compiles_total"
            )
            assert gauge.get("solve", "-") == 1.0
            assert gauge.get("decide", "-") == 1.0
        finally:
            runtime.close()
