"""Concurrency stress: the store, the KubeStore informer mirror, and
leader election under concurrent writers + watchers + candidate churn.

The reference's battletest runs every suite with the Go race detector
(reference: Makefile:25-31); Python has no -race, so the threaded paths
(Store's lock discipline, KubeStore's watch threads, lease CAS) get
hammered directly instead: many threads, real interleavings, invariants
checked at the end. Tests use fixed thread/op counts small enough to run
in seconds but large enough that a missing lock or torn notify fails in
practice (verified by removing locks locally during development).
"""

import threading
import time

import pytest

from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.leaderelection import LeaderElector
from karpenter_tpu.store import ConflictError, NotFoundError, Store
from karpenter_tpu.store.store import DELETED
from karpenter_tpu.store.kube import KubeClient, KubeStore
from tests.fake_apiserver import FakeApiServer

N_WRITERS = 8
OPS_PER_WRITER = 120


def sng(name, replicas=0):
    return ScalableNodeGroup(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=ScalableNodeGroupSpec(
            replicas=replicas, type="FakeNodeGroup", id=name
        ),
    )


def run_threads(targets):
    errors = []

    def wrap(fn):
        def runner():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — surfaced at the end
                errors.append(e)

        return runner

    threads = [threading.Thread(target=wrap(t)) for t in targets]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "stress thread deadlocked"
    return errors


class TestStoreUnderConcurrency:
    def test_writers_and_watchers_race_coherently(self):
        """N writers hammer overlapping keys (create/update/delete with
        conflict retries) while watchers subscribe mid-flight. Invariants:
        no exceptions escape, the final store state equals what a replay
        of each key's watcher stream predicts, and resourceVersions only
        ever increase per key."""
        store = Store()
        events = []
        events_lock = threading.Lock()

        def watcher(event, obj):
            with events_lock:
                events.append(
                    (event, obj.metadata.name, obj.metadata.resource_version)
                )

        store.watch("ScalableNodeGroup", watcher)

        def writer(wid):
            def run():
                for i in range(OPS_PER_WRITER):
                    name = f"g{(wid + i) % 5}"  # 5 shared keys -> conflicts
                    op = i % 3
                    try:
                        if op == 0:
                            store.create(sng(name, replicas=wid))
                        elif op == 1:
                            obj = store.try_get(
                                "ScalableNodeGroup", "default", name
                            )
                            if obj is not None:
                                obj.spec.replicas = wid * 1000 + i
                                store.update(obj)
                        else:
                            store.delete(
                                "ScalableNodeGroup", "default", name
                            )
                    except (ConflictError, NotFoundError):
                        pass  # the contention under test, not a failure

            return run

        errors = run_threads([writer(w) for w in range(N_WRITERS)])
        assert errors == [], errors

        # per-key resourceVersions in the watcher stream must be monotone
        last_rv = {}
        live_per_stream = {}
        for event, name, rv in events:
            if event != DELETED:
                assert rv > last_rv.get(name, 0), (name, rv, last_rv)
                last_rv[name] = rv
            live_per_stream[name] = event != DELETED
        # replaying each key's stream predicts the final store state
        for name, alive in live_per_stream.items():
            present = (
                store.try_get("ScalableNodeGroup", "default", name)
                is not None
            )
            assert present == alive, name

    def test_watch_subscription_during_write_storm(self):
        """Subscribing watchers while writes are in flight must neither
        deadlock nor corrupt the notify list."""
        store = Store()
        seen = []

        def writer():
            for i in range(200):
                store.create(sng(f"w{i}"))

        def subscriber():
            for _ in range(50):
                store.watch(
                    "ScalableNodeGroup", lambda e, o: seen.append(1)
                )

        errors = run_threads([writer, subscriber, subscriber])
        assert errors == []
        # the interleaving is timing-dependent (fast_clone made writes
        # quick enough to finish before subscribers start), so assert the
        # invariant directly: every registered watcher observes traffic
        # that happens after registration, and the notify list is intact
        before = len(seen)
        store.create(sng("after-storm"))
        assert len(seen) == before + 100  # all 2x50 watchers fired once


class TestKubeStoreUnderConcurrency:
    @pytest.fixture()
    def api(self):
        server = FakeApiServer()
        server.start()
        yield server
        server.stop()

    def test_concurrent_rest_writers_converge_mirror(self, api):
        """Writers race conflict-retried updates over real HTTP while the
        informer mirror ingests the watch stream; the mirror must converge
        exactly to the apiserver's final truth."""
        store = KubeStore(
            KubeClient(base_url=api.url, timeout=5.0), resync_backoff=0.05
        )
        try:
            for k in range(4):
                store.create(sng(f"g{k}"))

            def writer(wid):
                def run():
                    for i in range(40):
                        name = f"g{(wid + i) % 4}"
                        for _ in range(10):  # conflict-retry loop
                            try:
                                obj = store.client.get(
                                    "ScalableNodeGroup", "default", name
                                )
                                obj.spec.replicas = wid * 1000 + i
                                store.update(obj)
                                break
                            except ConflictError:
                                continue

                return run

            errors = run_threads([writer(w) for w in range(6)])
            assert errors == [], errors

            truth = {
                d["metadata"]["name"]: d["spec"].get("replicas")
                for d in api.objects("scalablenodegroups")
            }
            deadline = time.time() + 5
            while time.time() < deadline:
                mirrored = {
                    name: (
                        store.try_get("ScalableNodeGroup", "default", name)
                    )
                    for name in truth
                }
                if all(
                    m is not None and m.spec.replicas == truth[name]
                    for name, m in mirrored.items()
                ):
                    break
                time.sleep(0.02)
            for name in truth:
                got = store.get("ScalableNodeGroup", "default", name)
                assert got.spec.replicas == truth[name], name
        finally:
            store.close()


class TestLeaderElectionChurn:
    def test_at_most_one_leader_through_candidate_churn(self):
        """Candidates start, run election rounds, and abruptly stop (no
        graceful release) while every round records who believes it leads.
        Invariants: never two concurrent leaders, and after churn the
        survivors elect exactly one within a lease expiry."""
        store = Store()
        clock_lock = threading.Lock()
        clock_now = [1000.0]

        def clock():
            with clock_lock:
                return clock_now[0]

        def advance(dt):
            with clock_lock:
                clock_now[0] += dt

        state_lock = threading.Lock()
        in_critical = []  # identities currently acting on believed leadership
        violations = []
        ever_led = set()
        last_leader = {"id": None}
        stop = {"a": False, "b": False, "c": False, "d": False}

        def candidate(cid):
            elector = LeaderElector(
                store, identity=cid, lease_duration=5.0, clock=clock
            )

            def run():
                while not stop[cid]:
                    if elector.try_acquire():
                        # a LIVE leader renews every round, so another
                        # candidate can only take over once this one
                        # stops — two identities inside this critical
                        # section at the same real time is a safety bug
                        with state_lock:
                            in_critical.append(cid)
                            if len(set(in_critical)) > 1:
                                violations.append(tuple(in_critical))
                            ever_led.add(cid)
                            last_leader["id"] = cid
                        time.sleep(0.002)
                        with state_lock:
                            in_critical.remove(cid)
                    time.sleep(0.001)

            return run

        threads = {c: threading.Thread(target=candidate(c)) for c in stop}
        for t in threads.values():
            t.start()
        time.sleep(0.15)
        # kill the current leader, twice; advancing past lease expiry must
        # transfer leadership to a survivor. The victim is JOINED before
        # the clock jump: jumping while a live leader sleeps inside its
        # critical section simulates the paused-leader scenario, where
        # brief dual-belief is allowed by lease semantics (leases are not
        # fencing tokens) and would be a false positive here.
        for _ in range(2):
            with state_lock:
                victim = last_leader["id"]
            if victim and not stop[victim]:
                stop[victim] = True
                threads[victim].join(timeout=30)
                assert not threads[victim].is_alive()
            advance(6.0)
            time.sleep(0.2)
        for c in stop:
            stop[c] = True
        for t in threads.values():
            t.join(timeout=30)
            assert not t.is_alive()

        assert not violations, violations
        # leadership actually transferred through the churn (>= 3 distinct
        # leaders across two kills) and a lease object exists
        assert len(ever_led) >= 3, ever_led
        assert store.try_get("Lease", "kube-system", "karpenter-leader")


class TestPendingFeedUnderConcurrency:
    def test_pod_churn_races_with_snapshot_and_dedup(self):
        """N writers churn pending pods (create/update/delete, shared +
        distinct shapes, some with affinity) while a reader continuously
        snapshots and dedups. Invariants at quiesce: no exceptions, the
        incremental dedup's weights sum to the live pending count, and
        the cache's snapshot solves identically to a fresh detached
        encode over store.list (the oracle)."""
        import numpy as np

        import karpenter_tpu.metrics.producers.pendingcapacity as PC
        from karpenter_tpu.metrics.producers.pendingcapacity import encoder as PCE
        from karpenter_tpu.api.core import (
            Affinity,
            Container,
            NodeAffinity,
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            Pod,
            PodSpec,
            resource_list,
        )
        from karpenter_tpu.store.columnar import (
            PendingPodCache,
            snapshot_from_pods,
        )

        store = Store()
        cache = PendingPodCache(store)

        def pin(zone):
            return Affinity(
                node_affinity=NodeAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        NodeSelector(
                            node_selector_terms=[
                                NodeSelectorTerm(
                                    match_expressions=[
                                        NodeSelectorRequirement(
                                            key="zone",
                                            operator="In",
                                            values=[zone],
                                        )
                                    ]
                                )
                            ]
                        )
                    )
                )
            )

        cpus = ["100m", "250m", "1", "2"]

        def make_pod(name, i):
            return Pod(
                metadata=ObjectMeta(name=name, namespace="default"),
                spec=PodSpec(
                    containers=[
                        Container(
                            requests=resource_list(cpu=cpus[i % len(cpus)])
                        )
                    ],
                    affinity=pin(f"z{i % 3}") if i % 5 == 0 else None,
                ),
            )

        def writer(wid):
            def run():
                for i in range(OPS_PER_WRITER):
                    name = f"p{wid}-{i % 20}"  # per-writer keys, reused
                    op = i % 3
                    try:
                        if op == 0:
                            store.create(make_pod(name, i))
                        elif op == 1:
                            obj = store.try_get("Pod", "default", name)
                            if obj is not None:
                                store.update(make_pod(name, i + 1))
                        else:
                            store.delete("Pod", "default", name)
                    except (ConflictError, NotFoundError):
                        pass

            return run

        stop = threading.Event()

        def reader():
            while not stop.is_set():
                snap = cache.snapshot()
                idx, weights = PCE._dedup_rows(snap)
                # internal coherence mid-race: weights positive, indices
                # inside the snapshot
                assert (weights > 0).all()
                if len(idx):
                    assert int(idx.max()) < snap.requests.shape[0]

        reader_thread = threading.Thread(target=reader)
        reader_thread.start()
        try:
            errors = run_threads([writer(w) for w in range(N_WRITERS)])
        finally:
            stop.set()
            reader_thread.join(timeout=60)
        assert errors == [], errors
        assert not reader_thread.is_alive()

        live = store.list("Pod")
        snap = cache.snapshot()
        _, weights = PCE._dedup_rows(snap)
        assert int(np.sum(weights)) == len(live) == len(cache)

        # the watch-maintained cache must solve exactly like a fresh
        # detached encode of the store's current pods
        profiles = [
            ({"cpu": 8.0, "memory": 64.0 * 1024**3, "pods": 110.0},
             {("zone", "z0")}, set()),
            ({"cpu": 8.0, "memory": 64.0 * 1024**3, "pods": 110.0},
             {("zone", "z1")}, set()),
        ]
        from karpenter_tpu.ops import binpack as B

        got = B.binpack(PC.encode_snapshot(snap, profiles), buckets=8)
        want = B.binpack(
            PC.encode_snapshot(snapshot_from_pods(live), profiles),
            buckets=8,
        )
        np.testing.assert_array_equal(
            np.asarray(got.assigned_count), np.asarray(want.assigned_count)
        )
        np.testing.assert_array_equal(
            np.asarray(got.nodes_needed), np.asarray(want.nodes_needed)
        )
        assert int(got.unschedulable) == int(want.unschedulable)


class TestOccupancyUnderConcurrency:
    def test_bind_churn_races_with_census_queries(self):
        """Writers race pods through pending -> bound -> rebound ->
        deleted transitions while a reader hammers DomainCensus queries
        (the watch-event path mutates under the census lock the queries
        copy from). Invariants: no exceptions mid-race, and at quiesce
        the watch-maintained census equals a detached oracle build of
        the store's pods, and a fresh census query reflects exactly the
        final occupancy."""
        from karpenter_tpu.api.core import (
            Container,
            Node,
            ObjectMeta as OM,
            Pod,
            PodSpec,
            PodStatus,
            resource_list,
        )
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            DomainCensus,
        )
        from karpenter_tpu.store.columnar import (
            ScheduledOccupancy,
            occupancy_from_pods,
        )

        store = Store()
        census_backing = ScheduledOccupancy(store)
        nodes = [
            Node(
                metadata=OM(
                    name=f"n{i}",
                    labels={"zone": f"z{i % 3}"},
                )
            )
            for i in range(6)
        ]
        census = DomainCensus(census_backing, lambda: nodes)

        def make_pod(name, i, bound):
            return Pod(
                metadata=OM(
                    name=name,
                    namespace="default",
                    labels={"app": f"a{i % 4}"},
                ),
                spec=PodSpec(
                    node_name=f"n{i % 6}" if bound else "",
                    containers=[
                        Container(requests=resource_list(cpu="100m"))
                    ],
                ),
                status=PodStatus(
                    phase=("Running" if bound and i % 7 else "Pending")
                ),
            )

        def writer(wid):
            def run():
                for i in range(OPS_PER_WRITER):
                    name = f"p{wid}-{i % 15}"
                    op = i % 4
                    try:
                        if op == 0:
                            store.create(make_pod(name, i, bound=False))
                        elif op in (1, 2):
                            obj = store.try_get("Pod", "default", name)
                            if obj is not None:
                                store.update(
                                    make_pod(name, i, bound=True)
                                )
                        else:
                            store.delete("Pod", "default", name)
                    except (ConflictError, NotFoundError):
                        pass

            return run

        stop = threading.Event()
        sel = ((("app", "a1"),), ())
        reader_errors = []

        def reader():
            try:
                while not stop.is_set():
                    blocked = census.anti_domains(
                        "default", (sel,), ("zone",)
                    )
                    assert set(blocked) == {"zone"}
                    counts = census.domain_counts("default", sel, "zone")
                    assert all(v > 0 for v in counts.values())
            except Exception as e:  # noqa: BLE001 — surfaced below: a
                # swallowed reader failure would green-light the race
                reader_errors.append(e)

        reader_thread = threading.Thread(target=reader)
        reader_thread.start()
        try:
            errors = run_threads([writer(w) for w in range(N_WRITERS)])
        finally:
            stop.set()
            reader_thread.join(timeout=60)
        assert errors == [], errors
        assert reader_errors == [], reader_errors
        assert not reader_thread.is_alive()

        oracle = occupancy_from_pods(store.list("Pod"))
        with census_backing.view() as (_, live_spaces):
            with oracle.view() as (_, oracle_spaces):
                assert live_spaces == oracle_spaces

        # a fresh query sees exactly the final occupancy
        expected = {}
        for pod in store.list("Pod"):
            if pod.spec.node_name and pod.status.phase not in (
                "Succeeded",
                "Failed",
            ) and pod.metadata.labels.get("app") == "a1":
                zone = dict(
                    (n.metadata.name, n.metadata.labels["zone"])
                    for n in nodes
                )[pod.spec.node_name]
                expected[zone] = expected.get(zone, 0) + 1
        assert census.domain_counts("default", sel, "zone") == expected
