"""Replicated control plane: clock discipline, partition leases, fenced
handoff, the split-brain regression, and the seeded failover world.

The acceptance bar (ISSUE: replicated control plane):

  * a wall clock stepped backward cannot extend a stale lease, and one
    stepped forward within the skew tolerance cannot steal a fresh one;
  * two electors racing one lease resolve by CAS — the stale
    resourceVersion loser's fenced actuation is rejected with
    `FenceRejected` and the flight recorder attributes the rejection to
    the loser's trace;
  * killing the leader mid-storm reassigns its tenants to survivors and
    reconverges to the no-fault fixed point within 10 ticks, with zero
    duplicate and zero lost `set_replicas` writes (journal-audited);
  * without `--partitions` the runtime is byte-identical to the
    single-replica deployment: no replication plane, no Lease objects,
    no lease fault-point traffic, no karpenter_replica_* metrics.

`make test-failover` runs exactly this file.
"""

from __future__ import annotations

import json
import os
import urllib.request

import pytest

from karpenter_tpu import faults
from karpenter_tpu.faults import FaultRegistry, ProcessCrash
from karpenter_tpu.leaderelection import LeaderElector
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.recovery.fence import (
    FenceRejectedError,
    FenceValidator,
)
from karpenter_tpu.replication import (
    PartitionLeaseManager,
    ReplicatedControlPlane,
    SkewedClock,
    TenantHandoff,
    crash_plan,
    partition_of,
    partition_plans,
    rendezvous_rank,
)
from karpenter_tpu.store import Store
from karpenter_tpu.store.store import ConflictError

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_registry():
    yield
    faults.uninstall()


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


class TestClockDiscipline:
    """Satellite: monotonic lease expiry + skew tolerance in the
    LeaderElector."""

    def test_backward_wall_step_cannot_extend_stale_lease(self):
        """The holder dies; a candidate whose wall clock then steps
        BACKWARD (so wall expiry never fires) still takes over once its
        monotonic observation of the frozen stamp ages past the
        margin."""
        store = Store()
        wall = FakeClock(1000.0)
        holder = LeaderElector(
            store, identity="a", clock=wall, lease_duration=5.0
        )
        assert holder.try_acquire()
        # candidate: wall clock stepped back BEFORE the renew stamp, an
        # honest separate monotonic clock
        skewed = SkewedClock(wall, offset_s=-30.0)
        mono = FakeClock(0.0)
        candidate = LeaderElector(
            store, identity="b", clock=skewed, monotonic=mono,
            lease_duration=5.0,
        )
        # wall expiry can never fire: skewed now (970) < renew (1000)
        assert not candidate.try_acquire()
        mono.advance(4.0)  # within lease_duration + skew_tolerance
        assert not candidate.try_acquire()
        mono.advance(3.0)  # observation age 7 > 5 + 1: stale
        assert candidate.try_acquire()
        assert candidate.is_leader()

    def test_forward_step_within_skew_cannot_steal_fresh_lease(self):
        """A candidate whose wall clock runs ahead by less than
        lease_duration + skew_tolerance never preempts a holder that
        renews on time."""
        store = Store()
        wall = FakeClock(1000.0)
        holder = LeaderElector(
            store, identity="a", clock=wall, lease_duration=5.0
        )
        ahead = SkewedClock(wall, offset_s=5.5)  # < 5 + 1 margin
        candidate = LeaderElector(
            store, identity="b", clock=ahead, monotonic=FakeClock(0.0),
            lease_duration=5.0,
        )
        assert holder.try_acquire()
        for _ in range(20):
            wall.advance(2.0)  # holder renews well inside the lease
            assert holder.try_acquire()
            assert not candidate.try_acquire()
        assert holder.is_leader()

    def test_forward_step_past_margin_does_steal(self):
        """The complement: a skew larger than the margin IS a dead
        holder as far as the candidate can tell — takeover happens (and
        the fence, not the lease, is what protects actuation)."""
        store = Store()
        wall = FakeClock(1000.0)
        holder = LeaderElector(
            store, identity="a", clock=wall, lease_duration=5.0
        )
        ahead = SkewedClock(wall, offset_s=7.0)  # > 5 + 1 margin
        candidate = LeaderElector(
            store, identity="b", clock=ahead, monotonic=FakeClock(0.0),
            lease_duration=5.0,
        )
        assert holder.try_acquire()
        assert candidate.try_acquire()

    def test_own_leadership_lapses_on_monotonic_clock(self):
        """is_leader() is judged on OUR monotonic renew age, so a
        holder that stops renewing stops believing it leads even if the
        store still names it."""
        store = Store()
        wall = FakeClock(1000.0)
        mono = FakeClock(0.0)
        holder = LeaderElector(
            store, identity="a", clock=wall, monotonic=mono,
            lease_duration=5.0,
        )
        assert holder.try_acquire()
        assert holder.is_leader()
        mono.advance(6.0)  # no renew for > lease_duration
        assert not holder.is_leader()

    def test_release_allows_immediate_takeover(self):
        store, clock = Store(), FakeClock()
        a = LeaderElector(store, identity="a", clock=clock,
                          lease_duration=15.0)
        b = LeaderElector(store, identity="b", clock=clock,
                          lease_duration=15.0)
        assert a.try_acquire()
        assert not b.try_acquire()
        a.release()
        assert b.try_acquire()  # no lease_duration wait
        assert b.is_leader()


class TestSplitBrainRegression:
    """Satellite: two electors race one lease; the stale
    resourceVersion loser's fenced actuation is rejected and the flight
    recorder attributes the rejection to the loser's trace."""

    def test_stale_resource_version_loses_the_cas(self):
        store, clock = Store(), FakeClock()
        old = LeaderElector(store, identity="old", clock=clock,
                            lease_duration=5.0)
        new = LeaderElector(store, identity="new", clock=clock,
                            lease_duration=5.0)
        assert old.try_acquire()
        clock.advance(7.0)  # old partitioned: lease lapses
        # the race: old READS the expired lease, then new's takeover
        # lands first — old's update now carries a stale resourceVersion
        stale = store.try_get(
            "Lease", old.namespace, old.name
        )
        assert new.try_acquire()
        stale.holder = "old"
        stale.renew_time = clock()
        with pytest.raises(ConflictError):
            store.update(stale)
        assert new.is_leader()
        # and through the elector API the loser just loses the round
        assert not old.try_acquire()

    def test_loser_actuation_fence_rejected_and_recorded(self, tmp_path):
        from karpenter_tpu.observability import default_tracer
        from karpenter_tpu.observability.flightrecorder import (
            default_flight_recorder,
            reset_default_flight_recorder,
            set_default_flight_recorder,
        )

        journal_dir = str(tmp_path / "tenant")
        validator = FenceValidator()
        clock = FakeClock()
        # deposed owner claimed generation 1; the winner's adoption
        # claims generation 2 and seeds the provider validator
        deposed = TenantHandoff(
            "t0", journal_dir=journal_dir, validator=validator,
            clock=clock,
        )
        winner = TenantHandoff(
            "t0", journal_dir=journal_dir, validator=validator,
            clock=clock,
        )
        assert deposed.generation == 1
        assert winner.generation == 2
        saved = default_flight_recorder()
        recorder = reset_default_flight_recorder()
        try:
            tracer = default_tracer()
            with tracer.trace("reconcile-deposed") as span:
                loser_trace = span.trace_id
                with pytest.raises(FenceRejectedError) as err:
                    validator.admit(deposed.token())
                assert err.value.code == "FenceRejected"
                # the ScalableNodeGroup controller's rejection path
                # (controllers/scalablenodegroup.py)
                deposed.recovery.count_fence_rejection()
            events = recorder.events(kind="fence_rejection")
            assert len(events) == 1
            assert events[0]["generation"] == 1
            assert loser_trace in events[0]["trace_ids"]
        finally:
            set_default_flight_recorder(saved)
            deposed.release()
            winner.release()
        # the winner's stamp still lands
        validator.admit(winner.token())


class TestPartitionsAndRendezvous:
    def test_partition_of_is_deterministic_and_in_range(self):
        for tenant in (f"t{i}" for i in range(64)):
            p = partition_of(tenant, 8)
            assert 0 <= p < 8
            assert p == partition_of(tenant, 8)

    def test_partition_of_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            partition_of("t", 0)

    def test_rendezvous_rank_deterministic_and_complete(self):
        replicas = ["r0", "r1", "r2", "r3"]
        for partition in range(16):
            rank = rendezvous_rank(partition, replicas)
            assert sorted(rank) == sorted(replicas)
            assert rank == rendezvous_rank(
                partition, list(reversed(replicas))
            )

    def test_rendezvous_minimal_disruption(self):
        """The rendezvous property the sticky assignment leans on:
        removing a replica only moves the partitions IT topped — every
        other partition keeps its winner."""
        replicas = ["r0", "r1", "r2", "r3"]
        tops = {
            p: rendezvous_rank(p, replicas)[0] for p in range(64)
        }
        survivors = [r for r in replicas if r != "r1"]
        for p, top in tops.items():
            if top != "r1":
                assert rendezvous_rank(p, survivors)[0] == top


class TestPartitionLeases:
    def _manager(self, store, clock, replica_id, partitions=6):
        return PartitionLeaseManager(
            store, replica_id=replica_id, partitions=partitions,
            lease_duration=5.0, clock=clock,
        )

    def test_single_replica_owns_everything(self):
        store, clock = Store(), FakeClock()
        m = self._manager(store, clock, "a")
        m.round()  # observation round: heartbeat only
        clock.advance(1.0)
        round_ = m.round()
        assert round_.owned == set(range(6))
        assert round_.live == ["a"]

    def test_two_replicas_partition_disjointly(self):
        store, clock = Store(), FakeClock()
        a = self._manager(store, clock, "a")
        b = self._manager(store, clock, "b")
        for _ in range(3):
            clock.advance(1.0)
            a.round()
            b.round()
        assert a.owned | b.owned == set(range(6))
        assert not (a.owned & b.owned)
        assert a.owned  # rendezvous over 6 partitions gives both work
        assert b.owned

    def test_ownership_sticky_when_a_replica_joins(self):
        store, clock = Store(), FakeClock()
        a = self._manager(store, clock, "a")
        for _ in range(2):
            clock.advance(1.0)
            a.round()
        before = set(a.owned)
        assert before == set(range(6))
        c = self._manager(store, clock, "c")
        for _ in range(3):
            clock.advance(1.0)
            a.round()
            c.round()
        # the holder renews first every round: nothing moves
        assert a.owned == before
        assert not c.owned

    def test_dead_replica_partitions_adopted_after_expiry(self):
        store, clock = Store(), FakeClock()
        a = self._manager(store, clock, "a")
        b = self._manager(store, clock, "b")
        for _ in range(3):
            clock.advance(1.0)
            a.round()
            b.round()
        dead_partitions = set(a.owned)
        assert dead_partitions
        # a dies: no rounds, its heartbeat and partition leases lapse
        clock.advance(7.0)  # > lease_duration + skew
        for _ in range(2):
            clock.advance(1.0)
            b.round()
        assert b.owned == set(range(6))
        assert b.live_replicas() == ["b"]

    def test_release_all_hands_over_without_expiry_wait(self):
        store, clock = Store(), FakeClock()
        a = self._manager(store, clock, "a")
        b = self._manager(store, clock, "b")
        for _ in range(3):
            clock.advance(1.0)
            a.round()
            b.round()
        a.release_all()
        clock.advance(1.0)  # well inside the lease duration
        b.round()
        clock.advance(1.0)
        b.round()
        assert b.owned == set(range(6))


class TestTenantHandoff:
    def test_unfenced_warmup_gates_disruption(self):
        h = TenantHandoff("t", warmup_ticks=2)
        assert h.state == "warmup"
        assert not h.ready()
        assert not h.allow_disruption()
        h.on_tick()
        assert not h.ready()
        h.on_tick()
        assert h.ready()
        assert h.allow_disruption()
        assert h.state == "serving"
        h.release()
        assert h.state == "released"
        assert not h.ready()

    def test_fenced_adoption_replays_predecessor_intent(self, tmp_path):
        from karpenter_tpu.recovery.journal import key_str

        journal_dir = str(tmp_path / "tenant")
        first = TenantHandoff("t", journal_dir=journal_dir)
        first.recovery.handle("intent").set(("t",), {"desired": 7})
        first.release()  # checkpoints + closes
        second = TenantHandoff("t", journal_dir=journal_dir)
        try:
            assert second.generation == first.generation + 1
            table = second.recovery.table("intent")
            assert table[key_str(("t",))] == {"desired": 7}
        finally:
            second.release()


class TestReplicatedControlPlane:
    def _plane(self, store, clock, replica_id, tenants, registry=None,
               partitions=4):
        return ReplicatedControlPlane(
            store, replica_id=replica_id, partitions=partitions,
            lease_duration=5.0, tenants_source=lambda: tenants,
            warmup_ticks=1, registry=registry, clock=clock,
        )

    def test_adoption_metrics_and_scoreboard(self):
        store, clock = Store(), FakeClock()
        registry = GaugeRegistry()
        tenants = ["t0", "t1", "t2"]
        plane = self._plane(store, clock, "a", tenants, registry)
        assert plane.slo_source() is None  # no round yet
        plane.on_tick()
        clock.advance(1.0)
        plane.on_tick()
        assert {t for t in tenants if plane.owns(t)} == set(tenants)
        # adopted this tick: still warming -> mid-failover for the SLO
        assert plane.slo_source() is True
        clock.advance(1.0)
        plane.on_tick()
        assert plane.slo_source() is False
        assert all(plane.serving(t) for t in tenants)
        assert all(plane.allow_disruption(t) for t in tenants)
        board = plane.scoreboard()
        assert board["replica"] == "a"
        assert set(board["tenants"]) == set(tenants)
        assert board["adopted_total"] == 3
        assert all(
            info["state"] == "serving"
            for info in board["tenants"].values()
        )
        text = registry.expose_text()
        assert "karpenter_replica_partitions_owned" in text
        assert "karpenter_handoff_tenants_adopted_total" in text
        plane.close()
        assert plane.scoreboard()["tenants"] == {}

    def test_crash_plan_kills_the_tick(self):
        store, clock = Store(), FakeClock()
        plane = self._plane(store, clock, "a", ["t0"])
        registry = FaultRegistry(seed=1)
        crash_plan(registry, "a", times=1)
        faults.install(registry)
        with pytest.raises(ProcessCrash):
            plane.on_tick()
        faults.uninstall()
        plane.on_tick()  # the plan is spent: the next tick lives

    def test_partition_plans_cut_off_the_lease_store(self):
        store, clock = Store(), FakeClock()
        plane = self._plane(store, clock, "a", ["t0"])
        registry = FaultRegistry(seed=1)
        acquire_plan, renew_plan = partition_plans(registry, "a")
        faults.install(registry)
        for _ in range(4):
            clock.advance(1.0)
            round_ = plane.on_tick()
        assert not round_.owned  # never acquired anything
        assert acquire_plan.fired > 0
        assert renew_plan.fired == 0  # never held, so never renewed
        faults.uninstall()
        for _ in range(2):
            clock.advance(1.0)
            round_ = plane.on_tick()
        assert round_.owned == set(range(4))  # partition healed
        # partition the HOLDER: renew rounds now fail and are counted
        registry2 = FaultRegistry(seed=2)
        _, renew_plan2 = partition_plans(registry2, "a")
        faults.install(registry2)
        # past the renew throttle (lease/3) but still holding: the
        # round is a RENEW, and it fails
        clock.advance(2.0)
        round_ = plane.on_tick()
        assert renew_plan2.fired > 0
        assert round_.failures > 0
        assert not round_.owned  # renew failed: ownership lapses


FAILOVER_SEED = 20260807


@pytest.fixture(scope="module")
def failover_report():
    from karpenter_tpu.simulate import simulate_failover

    return simulate_failover(seed=FAILOVER_SEED)


class TestFailoverWorld:
    """The seeded leader-kill world (`--simulate --failover`): the
    ISSUE's acceptance criteria, asserted on one deterministic run."""

    def test_victim_tenants_reassigned(self, failover_report):
        r = failover_report
        assert r["victim"] is not None
        assert r["victim_tenants"]
        assert r["tenants_reassigned"] == r["victim_tenants"]
        assert set(r["adopters"].values()).isdisjoint({r["victim"], None})

    def test_reconverges_within_ten_ticks(self, failover_report):
        r = failover_report
        assert r["converged"]
        assert r["reconverge_ticks"] is not None
        assert r["reconverge_ticks"] <= 10

    def test_exactly_once_actuation_across_handoff(self, failover_report):
        assert failover_report["duplicate_actuations"] == 0
        assert failover_report["lost_actuations"] == 0

    def test_deposed_late_write_fence_rejected(self, failover_report):
        r = failover_report
        assert r["stale_write_rejected"]
        assert not r["stale_write_applied"]
        assert r["fence_rejections"] >= 1
        # every victim tenant was re-fenced by its adopter
        assert all(
            gen >= 2 for gen in r["fence_generations"].values()
        )

    def test_world_is_deterministic(self, failover_report):
        from karpenter_tpu.simulate import simulate_failover

        again = simulate_failover(seed=FAILOVER_SEED)
        assert again["writes_digest"] == failover_report["writes_digest"]
        assert again["reconverge_ticks"] == (
            failover_report["reconverge_ticks"]
        )


class TestSingleReplicaPath:
    """Satellite: without --partitions the runtime is byte-identical to
    the single-replica deployment — no replication plane, no lease
    traffic, no replica metrics."""

    def test_no_partitions_builds_nothing_and_touches_nothing(self):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        clock = FakeClock()
        registry = FaultRegistry(seed=0)
        lease_plans = partition_plans(registry)  # glob: every identity
        crash_plans = [
            registry.plan("replica.crash.*", mode="error")
        ]
        faults.install(registry)
        runtime = KarpenterRuntime(
            Options(),  # partitions defaults to 0
            cloud_provider_factory=FakeFactory(),
            clock=clock,
        )
        try:
            assert runtime.replication is None
            for _ in range(3):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            # no lease objects, no lease/replica fault-point traffic
            assert runtime.store.list("Lease") == []
            assert all(
                p.fired == 0 for p in lease_plans + crash_plans
            )
            text = runtime.registry.expose_text()
            assert "karpenter_replica_" not in text
            assert "karpenter_handoff_" not in text
        finally:
            faults.uninstall()
            runtime.close()

    def test_partitions_flag_builds_the_plane(self):
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        clock = FakeClock()
        runtime = KarpenterRuntime(
            Options(partitions=4, replica_id="r0", lease_duration_s=5.0),
            cloud_provider_factory=FakeFactory(),
            clock=clock,
        )
        try:
            assert runtime.replication is not None
            assert runtime.replication.replica_id == "r0"
            for _ in range(2):
                clock.advance(61.0)
                runtime.manager.reconcile_all()
            assert runtime.replication.leases.owned == set(range(4))
            assert runtime.store.list("Lease") != []
            text = runtime.registry.expose_text()
            assert "karpenter_replica_partitions_owned" in text
        finally:
            runtime.close()


class TestDebugReplicasEndpoint:
    def _get(self, port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}"
        ) as resp:
            return json.loads(resp.read())

    def test_disabled_without_replication(self):
        from karpenter_tpu.observability import MetricsServer

        server = MetricsServer(GaugeRegistry(), port=0, host="127.0.0.1")
        port = server.start()
        try:
            assert self._get(port, "/debug/replicas") == {
                "enabled": False
            }
        finally:
            server.stop()

    def test_scoreboard_served(self):
        from karpenter_tpu.observability import MetricsServer

        store, clock = Store(), FakeClock()
        plane = ReplicatedControlPlane(
            store, replica_id="a", partitions=2, lease_duration=5.0,
            tenants_source=lambda: ["t0"], clock=clock,
        )
        plane.on_tick()
        clock.advance(1.0)
        plane.on_tick()
        server = MetricsServer(
            GaugeRegistry(), port=0, host="127.0.0.1", replication=plane
        )
        port = server.start()
        try:
            board = self._get(port, "/debug/replicas")
            assert board["enabled"] is True
            assert board["replica"] == "a"
            assert board["owned"] == [0, 1]
            assert "t0" in board["tenants"]
        finally:
            server.stop()
            plane.close()


def _baseline():
    path = os.path.join(REPO_ROOT, "BASELINE.json")
    with open(path) as f:
        return json.load(f)


class TestFailoverRegressionGuard:
    def test_published_blackout_bounded(self):
        """Published bench-failover rows keep the handoff blackout
        within 3 lease durations with exactly-once actuation."""
        published = _baseline().get("published", {})
        records = {
            k: v for k, v in published.items() if " failover (" in k
        }
        if not records:
            pytest.skip(
                "no failover record in BASELINE.json — run "
                "`make bench-failover`"
            )
        for key, rec in records.items():
            assert rec["converged"], key
            assert rec["duplicate_actuations"] == 0, key
            assert rec["lost_actuations"] == 0, key
            assert rec["stale_write_rejected"], key
            assert rec["blackout_p99_s"] <= 3 * rec["lease_duration_s"], (
                f"{key}: handoff blackout regressed to "
                f"{rec['blackout_p99_s']}s"
            )
