"""Metrics layer: registry naming/exposition and client query semantics."""

import math

import pytest

from karpenter_tpu.api.horizontalautoscaler import (
    Metric,
    MetricTarget,
    PrometheusMetricSource,
)
from karpenter_tpu.metrics.clients import (
    MetricQueryError,
    MetricsClientFactory,
    RegistryMetricsClient,
    parse_instant_selector,
)
from karpenter_tpu.metrics.registry import GaugeRegistry


def metric_for(query):
    return Metric(
        prometheus=PrometheusMetricSource(
            query=query, target=MetricTarget(type="AverageValue", value=1)
        )
    )


class TestSelectorParsing:
    def test_bare_name(self):
        assert parse_instant_selector("karpenter_queue_length") == (
            "karpenter_queue_length",
            {},
        )

    def test_labels(self):
        name, labels = parse_instant_selector(
            'karpenter_queue_length{name="q", namespace="default"}'
        )
        assert name == "karpenter_queue_length"
        assert labels == {"name": "q", "namespace": "default"}

    @pytest.mark.parametrize(
        "bad",
        [
            "sum(rate(foo[5m]))",  # full PromQL unsupported
            'foo{name="a" other="b"}',  # missing comma: must error, not drop
            'foo{name=}',
            "foo{,}",
            "",
        ],
    )
    def test_bad_syntax_raises(self, bad):
        with pytest.raises(MetricQueryError):
            parse_instant_selector(bad)


class TestRegistryClient:
    def test_reads_gauge(self):
        registry = GaugeRegistry()
        registry.register("queue", "length").set("q", "default", 41.0)
        client = RegistryMetricsClient(registry)
        got = client.get_current_value(
            metric_for('karpenter_queue_length{name="q"}')
        )
        assert got.value == 41.0

    def test_instant_vector_of_one_enforced(self):
        """reference: prometheus.go:46-55"""
        registry = GaugeRegistry()
        vec = registry.register("queue", "length")
        client = RegistryMetricsClient(registry)
        spec = metric_for("karpenter_queue_length")
        with pytest.raises(MetricQueryError, match="got 0 series"):
            client.get_current_value(spec)
        vec.set("a", "default", 1.0)
        vec.set("b", "default", 2.0)
        with pytest.raises(MetricQueryError, match="got 2 series"):
            client.get_current_value(spec)

    def test_unknown_metric_name(self):
        client = RegistryMetricsClient(GaugeRegistry())
        with pytest.raises(MetricQueryError, match="no metric named"):
            client.get_current_value(metric_for('nope{name="q"}'))


class TestFactory:
    def test_prometheus_source_dispatch(self):
        factory = MetricsClientFactory(registry=GaugeRegistry())
        client = factory.for_metric(metric_for("foo"))
        assert isinstance(client, RegistryMetricsClient)


class TestExposition:
    def test_text_format_with_nan(self):
        registry = GaugeRegistry()
        registry.register("reserved_capacity", "cpu_utilization").set(
            "g", "default", math.nan
        )
        text = registry.expose_text()
        assert "# TYPE karpenter_reserved_capacity_cpu_utilization gauge" in text
        assert (
            'karpenter_reserved_capacity_cpu_utilization{name="g",namespace="default"} NaN'
            in text
        )


class TestRuntimeSelfMetrics:
    def test_manager_publishes_tick_and_reconcile_counts(self):
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.scalablenodegroup import (
            ScalableNodeGroup,
            ScalableNodeGroupSpec,
        )
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime

        provider = FakeFactory()
        provider.node_replicas["g"] = 1
        rt = KarpenterRuntime(cloud_provider_factory=provider)
        rt.store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(
                    replicas=1, type="FakeNodeGroup", id="g"
                ),
            )
        )
        rt.manager.reconcile_all()
        reg = rt.registry
        assert reg.gauge("runtime", "tick_seconds").get(
            "manager", "-"
        ) is not None
        assert reg.gauge("runtime", "reconciles_total").get(
            "ScalableNodeGroup", "-"
        ) == 1.0
        assert reg.gauge("runtime", "reconcile_errors_total").get(
            "ScalableNodeGroup", "-"
        ) in (None, 0.0)
        # counters expose the Prometheus counter TYPE, not gauge
        text = reg.expose_text()
        assert "# TYPE karpenter_runtime_reconciles_total counter" in text

    def test_encode_cache_counters(self):
        from karpenter_tpu.api.core import (
            Container,
            Node,
            NodeCondition,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
        )
        from karpenter_tpu.api.metricsproducer import (
            MetricsProducer,
            MetricsProducerSpec,
            PendingCapacitySpec,
        )
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
            solve_pending,
        )
        from karpenter_tpu.store import Store
        from karpenter_tpu.store.columnar import PendingFeed
        from karpenter_tpu.utils.quantity import Quantity

        store = Store()
        feed = PendingFeed(store, group_profile)
        store.create(
            Node(
                metadata=ObjectMeta(name="n", labels={"g": "a"}),
                status=NodeStatus(
                    allocatable={"cpu": Quantity.parse("8")},
                    conditions=[NodeCondition(type="Ready", status="True")],
                ),
            )
        )
        store.create(
            Pod(
                metadata=ObjectMeta(name="p"),
                spec=PodSpec(
                    containers=[
                        Container(requests={"cpu": Quantity.parse("1")})
                    ]
                ),
            )
        )
        mp = store.create(
            MetricsProducer(
                metadata=ObjectMeta(name="mp"),
                spec=MetricsProducerSpec(
                    pending_capacity=PendingCapacitySpec(
                        node_selector={"g": "a"}
                    )
                ),
            )
        )
        registry = GaugeRegistry()
        solve_pending(store, [mp], registry, feed=feed)
        solve_pending(store, [mp], registry, feed=feed)
        gauge = registry.gauge("runtime", "encode_cache_total")
        assert gauge.get("miss", "-") == 1.0
        assert gauge.get("hit", "-") == 1.0


class TestHistogramPercentile:
    """HistogramVec.percentile — the estimator behind the simulator
    report's and bench-journal's provisioning-lead p50/p99 columns —
    must apply Prometheus's histogram_quantile() semantics: linear
    interpolation within the bucket holding the rank, clamp-to-bound
    beyond the last finite bucket, None for an empty series."""

    def _hist(self):
        from karpenter_tpu.metrics.registry import GaugeRegistry

        registry = GaugeRegistry()
        return registry.register(
            "lead", "seconds", kind="histogram",
            buckets=(0.1, 1.0, 10.0),
        )

    def test_empty_series_is_none(self):
        hist = self._hist()
        assert hist.percentile("g", "default", 50) is None
        assert hist.percentile("missing", "default", 99) is None

    def test_linear_within_bucket_matches_prometheus(self):
        hist = self._hist()
        for _ in range(4):
            hist.observe("g", "default", 0.05)  # bucket (0, 0.1]
        for _ in range(4):
            hist.observe("g", "default", 5.0)  # bucket (1.0, 10.0]
        # rank 4 of 8 lands exactly at the first bucket's upper bound
        assert hist.percentile("g", "default", 50) == pytest.approx(0.1)
        # rank 6 sits halfway through the (1.0, 10.0] bucket's 4 samples
        assert hist.percentile("g", "default", 75) == pytest.approx(5.5)

    def test_overflow_clamps_to_last_finite_bound(self):
        hist = self._hist()
        for _ in range(10):
            hist.observe("g", "default", 100.0)  # all +Inf bucket
        assert hist.percentile("g", "default", 99) == pytest.approx(10.0)
