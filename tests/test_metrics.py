"""Metrics layer: registry naming/exposition and client query semantics."""

import math

import pytest

from karpenter_tpu.api.horizontalautoscaler import (
    Metric,
    MetricTarget,
    PrometheusMetricSource,
)
from karpenter_tpu.metrics.clients import (
    MetricQueryError,
    MetricsClientFactory,
    RegistryMetricsClient,
    parse_instant_selector,
)
from karpenter_tpu.metrics.registry import GaugeRegistry


def metric_for(query):
    return Metric(
        prometheus=PrometheusMetricSource(
            query=query, target=MetricTarget(type="AverageValue", value=1)
        )
    )


class TestSelectorParsing:
    def test_bare_name(self):
        assert parse_instant_selector("karpenter_queue_length") == (
            "karpenter_queue_length",
            {},
        )

    def test_labels(self):
        name, labels = parse_instant_selector(
            'karpenter_queue_length{name="q", namespace="default"}'
        )
        assert name == "karpenter_queue_length"
        assert labels == {"name": "q", "namespace": "default"}

    @pytest.mark.parametrize(
        "bad",
        [
            "sum(rate(foo[5m]))",  # full PromQL unsupported
            'foo{name="a" other="b"}',  # missing comma: must error, not drop
            'foo{name=}',
            "foo{,}",
            "",
        ],
    )
    def test_bad_syntax_raises(self, bad):
        with pytest.raises(MetricQueryError):
            parse_instant_selector(bad)


class TestRegistryClient:
    def test_reads_gauge(self):
        registry = GaugeRegistry()
        registry.register("queue", "length").set("q", "default", 41.0)
        client = RegistryMetricsClient(registry)
        got = client.get_current_value(
            metric_for('karpenter_queue_length{name="q"}')
        )
        assert got.value == 41.0

    def test_instant_vector_of_one_enforced(self):
        """reference: prometheus.go:46-55"""
        registry = GaugeRegistry()
        vec = registry.register("queue", "length")
        client = RegistryMetricsClient(registry)
        spec = metric_for("karpenter_queue_length")
        with pytest.raises(MetricQueryError, match="got 0 series"):
            client.get_current_value(spec)
        vec.set("a", "default", 1.0)
        vec.set("b", "default", 2.0)
        with pytest.raises(MetricQueryError, match="got 2 series"):
            client.get_current_value(spec)

    def test_unknown_metric_name(self):
        client = RegistryMetricsClient(GaugeRegistry())
        with pytest.raises(MetricQueryError, match="no metric named"):
            client.get_current_value(metric_for('nope{name="q"}'))


class TestFactory:
    def test_prometheus_source_dispatch(self):
        factory = MetricsClientFactory(registry=GaugeRegistry())
        client = factory.for_metric(metric_for("foo"))
        assert isinstance(client, RegistryMetricsClient)


class TestExposition:
    def test_text_format_with_nan(self):
        registry = GaugeRegistry()
        registry.register("reserved_capacity", "cpu_utilization").set(
            "g", "default", math.nan
        )
        text = registry.expose_text()
        assert "# TYPE karpenter_reserved_capacity_cpu_utilization gauge" in text
        assert (
            'karpenter_reserved_capacity_cpu_utilization{name="g",namespace="default"} NaN'
            in text
        )


class TestRuntimeSelfMetrics:
    def test_manager_publishes_tick_and_reconcile_counts(self):
        from karpenter_tpu.api.core import ObjectMeta
        from karpenter_tpu.api.scalablenodegroup import (
            ScalableNodeGroup,
            ScalableNodeGroupSpec,
        )
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime

        provider = FakeFactory()
        provider.node_replicas["g"] = 1
        rt = KarpenterRuntime(cloud_provider_factory=provider)
        rt.store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(
                    replicas=1, type="FakeNodeGroup", id="g"
                ),
            )
        )
        rt.manager.reconcile_all()
        reg = rt.registry
        assert reg.gauge("runtime", "tick_seconds").get(
            "manager", "-"
        ) is not None
        assert reg.gauge("runtime", "reconciles_total").get(
            "ScalableNodeGroup", "-"
        ) == 1.0
        assert reg.gauge("runtime", "reconcile_errors_total").get(
            "ScalableNodeGroup", "-"
        ) in (None, 0.0)
        # counters expose the Prometheus counter TYPE, not gauge
        text = reg.expose_text()
        assert "# TYPE karpenter_runtime_reconciles_total counter" in text

    def test_encode_cache_counters(self):
        from karpenter_tpu.api.core import (
            Container,
            Node,
            NodeCondition,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
        )
        from karpenter_tpu.api.metricsproducer import (
            MetricsProducer,
            MetricsProducerSpec,
            PendingCapacitySpec,
        )
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
            solve_pending,
        )
        from karpenter_tpu.store import Store
        from karpenter_tpu.store.columnar import PendingFeed
        from karpenter_tpu.utils.quantity import Quantity

        store = Store()
        feed = PendingFeed(store, group_profile)
        store.create(
            Node(
                metadata=ObjectMeta(name="n", labels={"g": "a"}),
                status=NodeStatus(
                    allocatable={"cpu": Quantity.parse("8")},
                    conditions=[NodeCondition(type="Ready", status="True")],
                ),
            )
        )
        store.create(
            Pod(
                metadata=ObjectMeta(name="p"),
                spec=PodSpec(
                    containers=[
                        Container(requests={"cpu": Quantity.parse("1")})
                    ]
                ),
            )
        )
        mp = store.create(
            MetricsProducer(
                metadata=ObjectMeta(name="mp"),
                spec=MetricsProducerSpec(
                    pending_capacity=PendingCapacitySpec(
                        node_selector={"g": "a"}
                    )
                ),
            )
        )
        registry = GaugeRegistry()
        solve_pending(store, [mp], registry, feed=feed)
        solve_pending(store, [mp], registry, feed=feed)
        gauge = registry.gauge("runtime", "encode_cache_total")
        assert gauge.get("miss", "-") == 1.0
        assert gauge.get("hit", "-") == 1.0


class TestMetricsDocDrift:
    """Doc-drift lint (extends the exposition-lint suite): every
    `karpenter_*` family registered in code must appear in
    docs/OPERATIONS.md's "Metrics reference" table, and every
    documented family must still exist in code — PR 10/11 both shipped
    frozen-series/undocumented-gauge bugs this would have caught. Also
    enforces the unit-suffix discipline: `_seconds`/`_ms`/`_bytes`
    families must declare the matching unit, `_total` families must be
    counters with unit "count"."""

    # families registered through data-driven loops the AST scanner
    # cannot resolve (each pointer names the loop)
    EXPLICIT_FAMILIES = {
        # pendingcapacity/__init__.register_gauges: for name in (...)
        "karpenter_pending_capacity_pending_pods": "gauge",
        "karpenter_pending_capacity_additional_nodes_needed": "gauge",
        "karpenter_pending_capacity_lp_lower_bound": "gauge",
        "karpenter_pending_capacity_unschedulable_pods": "gauge",
    }
    # families whose NAME is dynamic (documented as a pattern row)
    DYNAMIC_PREFIXES = (
        # reservedcapacity.register_gauges: f"{resource}_{metric_type}"
        "karpenter_reserved_capacity_",
    )

    @staticmethod
    def _module_constants(tree):
        import ast

        consts = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if (
                    isinstance(target, ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    consts[target.id] = node.value.value
        return consts

    def _scan_code_families(self):
        """AST scan of karpenter_tpu/ for `<registry>.register(sub,
        name, kind=...)` calls (incl. the `reg = registry.register`
        alias), resolving literal args and module-level string
        constants."""
        import ast
        import os

        root_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "karpenter_tpu",
        )
        families = dict(self.EXPLICIT_FAMILIES)
        for root, dirs, files in os.walk(root_dir):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for file_name in files:
                if not file_name.endswith(".py"):
                    continue
                path = os.path.join(root, file_name)
                tree = ast.parse(open(path).read())
                consts = self._module_constants(tree)

                def resolve(arg):
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str
                    ):
                        return arg.value
                    if isinstance(arg, ast.Name):
                        return consts.get(arg.id)
                    return None

                for node in ast.walk(tree):
                    if not isinstance(node, ast.Call):
                        continue
                    is_register = (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "register"
                    ) or (
                        isinstance(node.func, ast.Name)
                        and node.func.id == "reg"
                    )
                    if not is_register or len(node.args) < 2:
                        continue
                    sub = resolve(node.args[0])
                    name = resolve(node.args[1])
                    if sub is None or name is None:
                        continue  # not a metric register / dynamic name
                    kind = "gauge"
                    for kw in node.keywords:
                        if kw.arg == "kind" and isinstance(
                            kw.value, ast.Constant
                        ):
                            kind = kw.value.value
                    families[f"karpenter_{sub}_{name}"] = kind
        return families

    def _doc_rows(self):
        """(family, kind, unit) rows of the OPERATIONS.md table;
        pattern rows keep their `<...>` placeholders."""
        import os
        import re

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "docs", "OPERATIONS.md",
        )
        text = open(path).read()
        section = text.split("## Metrics reference", 1)
        assert len(section) == 2, (
            "docs/OPERATIONS.md must carry the 'Metrics reference' table"
        )
        body = section[1].split("\n## ", 1)[0]
        rows = []
        for match in re.finditer(
            r"^\| `(karpenter_[^`]+)` \| (\w+) \| ([^|]+) \|",
            body, re.MULTILINE,
        ):
            rows.append((
                match.group(1), match.group(2), match.group(3).strip()
            ))
        assert rows, "the Metrics reference table parsed empty"
        return rows

    def test_every_code_family_is_documented(self):
        code = self._scan_code_families()
        documented = {family for family, _k, _u in self._doc_rows()}
        missing = {
            family for family in code
            if family not in documented
            and not family.startswith(self.DYNAMIC_PREFIXES)
        }
        assert not missing, (
            f"registered but undocumented in docs/OPERATIONS.md "
            f"'Metrics reference': {sorted(missing)}"
        )

    def test_every_documented_family_exists_in_code(self):
        code = self._scan_code_families()
        stale = {
            family for family, _k, _u in self._doc_rows()
            if "<" not in family  # pattern rows match by prefix
            and family not in code
        }
        assert not stale, (
            f"documented in docs/OPERATIONS.md but not registered "
            f"anywhere in code: {sorted(stale)}"
        )
        # every pattern row's prefix must correspond to a known
        # dynamic-name registration
        patterns = [
            family for family, _k, _u in self._doc_rows()
            if "<" in family
        ]
        for pattern in patterns:
            prefix = pattern.split("<", 1)[0]
            assert prefix in self.DYNAMIC_PREFIXES, (
                f"pattern row {pattern} has no dynamic registration"
            )

    def test_kinds_and_unit_suffixes_agree(self):
        code = self._scan_code_families()
        for family, kind, unit in self._doc_rows():
            if "<" in family:
                continue
            assert kind == code[family], (
                f"{family}: documented as {kind}, registered as "
                f"{code[family]}"
            )
            if family.endswith("_total"):
                assert kind == "counter" and unit == "count", (
                    f"{family}: _total families are counters with "
                    f"unit 'count' (doc says {kind}/{unit})"
                )
            elif family.endswith("_seconds"):
                assert unit == "seconds", (
                    f"{family}: _seconds family documented as {unit}"
                )
            elif family.endswith("_ms"):
                assert unit == "ms", (
                    f"{family}: _ms family documented as {unit}"
                )
            elif family.endswith("_bytes"):
                assert unit == "bytes", (
                    f"{family}: _bytes family documented as {unit}"
                )
        # the reverse unit audit: any family documented with a time
        # unit must carry the matching suffix — the ms-vs-seconds
        # dashboard trap the PR 9 migration note warned about
        for family, _kind, unit in self._doc_rows():
            if "<" in family:
                continue
            if unit == "seconds":
                assert family.endswith("_seconds"), (
                    f"{family}: seconds-valued family must carry the "
                    f"_seconds suffix"
                )
            if unit == "ms":
                assert family.endswith("_ms"), (
                    f"{family}: millisecond-valued family must carry "
                    f"the _ms suffix"
                )


class TestHistogramPercentile:
    """HistogramVec.percentile — the estimator behind the simulator
    report's and bench-journal's provisioning-lead p50/p99 columns —
    must apply Prometheus's histogram_quantile() semantics: linear
    interpolation within the bucket holding the rank, clamp-to-bound
    beyond the last finite bucket, None for an empty series."""

    def _hist(self):
        from karpenter_tpu.metrics.registry import GaugeRegistry

        registry = GaugeRegistry()
        return registry.register(
            "lead", "seconds", kind="histogram",
            buckets=(0.1, 1.0, 10.0),
        )

    def test_empty_series_is_none(self):
        hist = self._hist()
        assert hist.percentile("g", "default", 50) is None
        assert hist.percentile("missing", "default", 99) is None

    def test_linear_within_bucket_matches_prometheus(self):
        hist = self._hist()
        for _ in range(4):
            hist.observe("g", "default", 0.05)  # bucket (0, 0.1]
        for _ in range(4):
            hist.observe("g", "default", 5.0)  # bucket (1.0, 10.0]
        # rank 4 of 8 lands exactly at the first bucket's upper bound
        assert hist.percentile("g", "default", 50) == pytest.approx(0.1)
        # rank 6 sits halfway through the (1.0, 10.0] bucket's 4 samples
        assert hist.percentile("g", "default", 75) == pytest.approx(5.5)

    def test_overflow_clamps_to_last_finite_bound(self):
        hist = self._hist()
        for _ in range(10):
            hist.observe("g", "default", 100.0)  # all +Inf bucket
        assert hist.percentile("g", "default", 99) == pytest.approx(10.0)
