"""PoolGroups (PR 20): the joint-allocation kernel, the engine, and
the wire-compat contracts (docs/poolgroups.md).

The pins mirror the cost-subsystem discipline one rank up:

  * numpy == XLA bitwise on every output leaf, both enforce modes —
    the mirror IS the device program;
  * joint == independent per-pool cost ladders when the declared
    couplings are slack — a PoolGroup whose constraints don't bind is
    byte-identical to the ungrouped plane;
  * an ungrouped fleet is byte-identical with --poolgroups set or
    unset — the subsystem's zero-overhead opt-out;
  * the engine never blocks: a failing joint seam leaves the base
    decisions standing and counts the degradation;
  * group gauges retire with the group (the frozen-series discipline);
  * tenants sharing a PoolGroup ride the same admission round.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.api.horizontalautoscaler import (
    Behavior,
    CrossVersionObjectReference,
    HorizontalAutoscaler,
    HorizontalAutoscalerSpec,
    Metric,
    MetricTarget,
    PrometheusMetricSource,
    SLOSpec,
)
from karpenter_tpu.api.poolgroup import (
    PoolGroup,
    PoolGroupSpec,
    PoolMember,
    RatioConstraint,
)
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.autoscaler import BatchAutoscaler
from karpenter_tpu.cost import CostEngine
from karpenter_tpu.metrics.clients import MetricsClientFactory
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.ops import cost as CK
from karpenter_tpu.ops import poolgroup as PGK
from karpenter_tpu.poolgroups import PoolGroupEngine
from karpenter_tpu.store import Store

PREFILL = 11  # queue 41 / AverageValue target 4 -> ceil
DECODE = 40  # queue 160 / 4


def random_group_inputs(
    seed: int, g: int = 4, p: int = 4, m: int = 2
) -> PGK.PoolGroupInputs:
    rng = np.random.RandomState(seed)
    base = rng.randint(0, 100, (g, p)).astype(np.int32)
    ratio_a = rng.randint(0, p, (g, PGK.RATIO_SLOTS)).astype(np.int32)
    ratio_b = rng.randint(0, p, (g, PGK.RATIO_SLOTS)).astype(np.int32)
    return PGK.PoolGroupInputs(
        base_desired=base,
        min_replicas=rng.randint(0, 5, (g, p)).astype(np.int32),
        max_replicas=(base + rng.randint(0, 300, (g, p))).astype(
            np.int32
        ),
        unit_cost=rng.choice(
            [0.0, 0.07, 0.3, 1.7, 12.5], (g, p)
        ).astype(np.float32),
        slo_weight=rng.choice([0.0, 1.0, 50.0, 333.3], (g, p)).astype(
            np.float32
        ),
        max_hourly_cost=rng.choice([0.0, 2.0, 55.5], (g, p)).astype(
            np.float32
        ),
        tier_penalty=rng.choice([0.0, 0.1, 2.0], (g, p)).astype(
            np.float32
        ),
        pool_valid=rng.rand(g, p) > 0.25,
        slo_target=rng.uniform(0.5, 10, (g, p, m)).astype(np.float32),
        demand_mu=rng.uniform(0, 500, (g, p, m)).astype(np.float32),
        demand_sigma=rng.choice([0.0, 3.0, 25.0], (g, p, m)).astype(
            np.float32
        ),
        demand_valid=rng.rand(g, p, m) > 0.2,
        ratio_a=ratio_a,
        # a == b would be a degenerate self-ratio the api layer rejects;
        # keep generated bands honest by bumping collisions off-diagonal
        ratio_b=np.where(
            ratio_a == ratio_b, (ratio_b + 1) % p, ratio_b
        ).astype(np.int32),
        ratio_min_num=rng.randint(
            0, 6, (g, PGK.RATIO_SLOTS)
        ).astype(np.int32),
        ratio_min_den=rng.randint(
            1, 4, (g, PGK.RATIO_SLOTS)
        ).astype(np.int32),
        ratio_max_num=rng.choice(
            [0, 4, 8, 1024], (g, PGK.RATIO_SLOTS)
        ).astype(np.int32),
        ratio_max_den=rng.choice(
            [1, 2], (g, PGK.RATIO_SLOTS)
        ).astype(np.int32),
        ratio_valid=rng.rand(g, PGK.RATIO_SLOTS) > 0.4,
        group_budget=rng.choice([0.0, 40.0, 400.0], g).astype(
            np.float32
        ),
        group_valid=rng.rand(g) > 0.2,
    )


class TestJointKernelParity:
    def test_xla_matches_numpy_bitwise_all_leaves(self):
        """The parity contract, both rungs: the enforcing joint program
        and the degraded independent program each match their numpy
        mirror bit for bit on EVERY output leaf."""
        for seed in range(6):
            for g, p, m in ((4, 4, 2), (1, 2, 1), (8, 3, 4)):
                inputs = random_group_inputs(seed, g, p, m)
                for dev_fn, enforce in (
                    (PGK.poolgroup_jit, True),
                    (PGK.poolgroup_independent_jit, False),
                ):
                    dev = dev_fn(inputs)
                    host = PGK.poolgroup_numpy(inputs, enforce=enforce)
                    for f in dataclasses.fields(PGK.PoolGroupOutputs):
                        a = np.asarray(getattr(dev, f.name))
                        b = np.asarray(getattr(host, f.name))
                        assert np.array_equal(a, b), (
                            f"seed={seed} g={g} p={p} m={m} "
                            f"enforce={enforce}: {f.name} diverged"
                        )

    def test_slack_constraints_match_the_per_pool_cost_ladder(self):
        """Wire compat one rank down: with every ratio and budget slack
        (invalid), each pool's joint choice equals what the PR 10 cost
        kernel picks for the identical operands — the joint program IS
        N cost ladders plus constraint selection, bit for bit."""
        for seed in range(4):
            inputs = random_group_inputs(seed, g=4, p=4, m=3)
            inputs = dataclasses.replace(
                inputs,
                tier_penalty=np.zeros_like(inputs.tier_penalty),
                ratio_valid=np.zeros_like(inputs.ratio_valid),
                group_valid=np.zeros_like(inputs.group_valid),
            )
            joint = PGK.poolgroup_jit(inputs)
            flat = CK.cost_jit(CK.CostInputs(
                base_desired=inputs.base_desired.reshape(-1),
                min_replicas=inputs.min_replicas.reshape(-1),
                max_replicas=inputs.max_replicas.reshape(-1),
                unit_cost=inputs.unit_cost.reshape(-1),
                slo_weight=inputs.slo_weight.reshape(-1),
                max_hourly_cost=inputs.max_hourly_cost.reshape(-1),
                slo_valid=inputs.pool_valid.reshape(-1),
                slo_target=inputs.slo_target.reshape(
                    -1, inputs.slo_target.shape[-1]
                ),
                demand_mu=inputs.demand_mu.reshape(
                    -1, inputs.demand_mu.shape[-1]
                ),
                demand_sigma=inputs.demand_sigma.reshape(
                    -1, inputs.demand_sigma.shape[-1]
                ),
                demand_valid=inputs.demand_valid.reshape(
                    -1, inputs.demand_valid.shape[-1]
                ),
            ))
            assert np.array_equal(
                np.asarray(joint.desired).reshape(-1),
                np.asarray(flat.desired),
            ), f"seed={seed}: joint != per-pool cost ladder"
            assert not np.asarray(joint.joint_repair).any()

    def test_invalid_pools_pass_through_exactly(self):
        inputs = random_group_inputs(2)
        inputs = dataclasses.replace(
            inputs, pool_valid=np.zeros_like(inputs.pool_valid)
        )
        out = PGK.poolgroup_jit(inputs)
        assert np.array_equal(
            np.asarray(out.desired), np.asarray(inputs.base_desired)
        )

    def test_repair_raises_a_pool_into_the_band(self):
        """A min-band the independent points violate, reachable within
        the candidate ladder: the joint selection raises the numerator
        pool (decode 40 -> 44 under decode:prefill >= 4:1) instead of
        serving the cheap violating point."""
        g, p, m = 1, PGK.pad_pool_count(2), 1
        inputs = PGK.PoolGroupInputs(
            base_desired=np.asarray([[11, 40]], np.int32).repeat(
                1, axis=0
            ),
            min_replicas=np.zeros((g, p), np.int32),
            max_replicas=np.full((g, p), 1000, np.int32),
            unit_cost=np.ones((g, p), np.float32),
            slo_weight=np.zeros((g, p), np.float32),
            max_hourly_cost=np.zeros((g, p), np.float32),
            tier_penalty=np.zeros((g, p), np.float32),
            pool_valid=np.asarray([[True, True]]),
            slo_target=np.ones((g, p, m), np.float32),
            demand_mu=np.zeros((g, p, m), np.float32),
            demand_sigma=np.zeros((g, p, m), np.float32),
            demand_valid=np.zeros((g, p, m), bool),
            ratio_a=np.asarray([[1] + [0] * 3], np.int32),
            ratio_b=np.asarray([[0] + [1] * 3], np.int32),
            ratio_min_num=np.asarray([[4] + [0] * 3], np.int32),
            ratio_min_den=np.ones((g, PGK.RATIO_SLOTS), np.int32),
            ratio_max_num=np.zeros((g, PGK.RATIO_SLOTS), np.int32),
            ratio_max_den=np.zeros((g, PGK.RATIO_SLOTS), np.int32),
            ratio_valid=np.asarray([[True, False, False, False]]),
            group_budget=np.zeros(g, np.float32),
            group_valid=np.asarray([True]),
        )
        if p > 2:
            inputs = dataclasses.replace(
                inputs,
                base_desired=np.pad(
                    np.asarray([[11, 40]], np.int32),
                    ((0, 0), (0, p - 2)),
                ),
                pool_valid=np.pad(
                    np.asarray([[True, True]]), ((0, 0), (0, p - 2))
                ),
            )
        out = PGK.poolgroup_jit(inputs)
        assert int(np.asarray(out.desired)[0, 0]) == 11
        assert int(np.asarray(out.desired)[0, 1]) == 44
        assert bool(np.asarray(out.ratio_ok)[0])
        assert bool(np.asarray(out.joint_repair)[0])
        # the degraded rung pins the independent point and reports the
        # violation honestly
        deg = PGK.poolgroup_numpy(inputs, enforce=False)
        assert int(np.asarray(deg.desired)[0, 1]) == 40
        assert not bool(np.asarray(deg.ratio_ok)[0])


class TestPoolGroupValidation:
    def _group(self, **spec):
        base = dict(
            pools=[PoolMember(name="a"), PoolMember(name="b")],
            ratios=[],
        )
        base.update(spec)
        return PoolGroup(
            metadata=ObjectMeta(name="g"), spec=PoolGroupSpec(**base)
        )

    def test_pool_count_bounds(self):
        with pytest.raises(ValueError, match="2..4 pools"):
            self._group(pools=[PoolMember(name="a")]).validate()
        with pytest.raises(ValueError, match="2..4 pools"):
            self._group(
                pools=[PoolMember(name=f"p{i}") for i in range(5)]
            ).validate()

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError, match="unique"):
            self._group(
                pools=[PoolMember(name="a"), PoolMember(name="a")]
            ).validate()

    def test_ratio_must_reference_declared_pools(self):
        with pytest.raises(ValueError, match="unknown pool"):
            self._group(ratios=[RatioConstraint(
                numerator="a", denominator="ghost", min_numerator=1,
            )]).validate()

    def test_empty_band_rejected(self):
        with pytest.raises(ValueError, match="band is empty"):
            self._group(ratios=[RatioConstraint(
                numerator="a", denominator="b",
                min_numerator=4, min_denominator=1,
                max_numerator=2, max_denominator=1,
            )]).validate()

    def test_ratio_slot_limit(self):
        ratios = [
            RatioConstraint(
                numerator="a", denominator="b", min_numerator=i + 1
            )
            for i in range(5)
        ]
        with pytest.raises(ValueError, match="at most 4 ratio"):
            self._group(ratios=ratios).validate()

    def test_role_alias_resolves(self):
        group = self._group(pools=[
            PoolMember(name="x", role="prefill"),
            PoolMember(name="y", role="decode"),
        ])
        assert group.member_index("decode") == 1
        assert group.member_index("x") == 0

    def test_kernel_limits_mirror_the_api(self):
        import karpenter_tpu.api.poolgroup as api_pg

        assert api_pg.MAX_POOLS == PGK.MAX_POOLS
        assert api_pg.RATIO_SLOTS == PGK.RATIO_SLOTS
        assert api_pg.RATIO_BOUND == PGK.RATIO_BOUND


def _world(groups=(), pool_engine=True, poolgroup_fn=None, slo=True):
    """A two-pool fleet (prefill queue 41, decode queue 160, target 4)
    with the given PoolGroup objects; returns (store, registry, auto,
    engine)."""
    store = Store()
    registry = GaugeRegistry()
    queue = registry.register("queue", "length")
    queue.set("qp", "default", 41.0)
    queue.set("qd", "default", 160.0)
    for name, q in (("prefill", "qp"), ("decode", "qd")):
        store.create(ScalableNodeGroup(
            metadata=ObjectMeta(name=f"g-{name}"),
            spec=ScalableNodeGroupSpec(
                replicas=5, type="FakeNodeGroup", id=f"g-{name}"
            ),
        ))
        store.create(HorizontalAutoscaler(
            metadata=ObjectMeta(name=name),
            spec=HorizontalAutoscalerSpec(
                scale_target_ref=CrossVersionObjectReference(
                    kind="ScalableNodeGroup", name=f"g-{name}"
                ),
                min_replicas=1,
                max_replicas=1000,
                metrics=[Metric(prometheus=PrometheusMetricSource(
                    query=f'karpenter_queue_length{{name="{q}"}}',
                    target=MetricTarget(type="AverageValue", value=4),
                ))],
                behavior=Behavior(
                    slo=SLOSpec(violation_cost_weight=100.0)
                    if slo else None
                ),
            ),
        ))
    for group in groups:
        store.create(group)
    engine = None
    if pool_engine:
        engine = PoolGroupEngine(
            store=store, poolgroup_fn=poolgroup_fn, registry=registry
        )
    auto = BatchAutoscaler(
        MetricsClientFactory(registry=registry), store,
        cost_engine=CostEngine(store=store, registry=registry),
        pool_engine=engine,
    )
    return store, registry, auto, engine


def _tick(store, auto):
    has = [
        store.get("HorizontalAutoscaler", "default", n)
        for n in ("prefill", "decode")
    ]
    errs = auto.reconcile_batch(has)
    assert all(e is None for e in errs.values()), errs
    return {
        n: store.get_scale(
            "ScalableNodeGroup", "default", f"g-{n}"
        ).spec_replicas
        for n in ("prefill", "decode")
    }


def _serving_group(ratios, name="serving", pools=None):
    return PoolGroup(
        metadata=ObjectMeta(name=name),
        spec=PoolGroupSpec(
            pools=pools or [
                PoolMember(name="prefill"), PoolMember(name="decode")
            ],
            ratios=ratios,
        ),
    )


SLACK_BAND = [RatioConstraint(
    numerator="decode", denominator="prefill",
    min_numerator=2, min_denominator=1,
    max_numerator=8, max_denominator=1,
)]  # 40/11 = 3.6: the independent points already satisfy it

REPAIR_BAND = [RatioConstraint(
    numerator="decode", denominator="prefill",
    min_numerator=4, min_denominator=1,
)]  # needs decode 44: in ladder reach of the independent 40


class TestWireCompat:
    def test_ungrouped_fleet_byte_identical_with_engine_on(self):
        """Zero-overhead opt-out: no PoolGroup objects -> the engine's
        plan is None and the wire is byte-identical to a fleet with no
        pool engine at all."""
        store_a, _, auto_a, _ = _world(pool_engine=True)
        store_b, _, auto_b, _ = _world(pool_engine=False)
        for _ in range(3):
            assert _tick(store_a, auto_a) == _tick(store_b, auto_b)
        for name in ("prefill", "decode"):
            a = store_a.get("HorizontalAutoscaler", "default", name)
            b = store_b.get("HorizontalAutoscaler", "default", name)
            assert a.status.desired_replicas == b.status.desired_replicas

    def test_slack_band_matches_the_ungrouped_plane(self):
        """joint == independent when the declared couplings don't bind:
        the grouped fleet lands on exactly the ungrouped counts."""
        store_g, _, auto_g, _ = _world(groups=[_serving_group(SLACK_BAND)])
        store_u, _, auto_u, _ = _world(pool_engine=False)
        for _ in range(3):
            assert _tick(store_g, auto_g) == _tick(store_u, auto_u)
        group = store_g.get("PoolGroup", "default", "serving")
        assert group.status.coordinated is True

    def test_repair_band_raises_decode_into_the_band(self):
        store, _, auto, _ = _world(groups=[_serving_group(REPAIR_BAND)])
        assert _tick(store, auto) == {"prefill": PREFILL, "decode": 44}
        group = store.get("PoolGroup", "default", "serving")
        assert group.status.coordinated is True
        assert group.status.expected_hourly == 55.0

    def test_fused_tick_matches_the_chained_path(self):
        """The --fused-tick joint stage lands tick-for-tick on the
        chained engine path's counts, repair included."""
        import jax

        from karpenter_tpu.ops import fusedtick as FT

        store_c, _, auto_c, _ = _world(groups=[_serving_group(REPAIR_BAND)])
        store_f, _, auto_f, _ = _world(groups=[_serving_group(REPAIR_BAND)])
        auto_f.fused_tick_fn = jax.jit(FT.fused_tick)
        for _ in range(3):
            assert _tick(store_c, auto_c) == _tick(store_f, auto_f)


class TestPoolGroupEngine:
    def test_unresolvable_member_sits_the_group_out(self):
        """A group naming a missing HA is skipped WHOLE — the live
        members keep their independent counts rather than being jointly
        allocated against a phantom."""
        ghost = _serving_group(
            [], pools=[
                PoolMember(name="prefill"), PoolMember(name="ghost")
            ],
        )
        store, registry, auto, _ = _world(groups=[ghost])
        assert _tick(store, auto) == {
            "prefill": PREFILL, "decode": DECODE
        }
        assert registry.gauge("poolgroup", "ratio_ok").get(
            "serving", "default"
        ) is None

    def test_overlapping_groups_first_listed_wins(self):
        first = _serving_group(REPAIR_BAND, name="a-first")
        second = _serving_group(SLACK_BAND, name="b-second")
        store, registry, auto, _ = _world(groups=[first, second])
        assert _tick(store, auto) == {"prefill": PREFILL, "decode": 44}
        assert registry.gauge("poolgroup", "ratio_ok").get(
            "a-first", "default"
        ) == 1.0
        assert registry.gauge("poolgroup", "ratio_ok").get(
            "b-second", "default"
        ) is None

    def test_failing_seam_never_blocks_and_counts_degraded(self):
        def boom(inputs):
            raise RuntimeError("joint seam down")

        store, registry, auto, _ = _world(
            groups=[_serving_group(REPAIR_BAND)], poolgroup_fn=boom
        )
        assert _tick(store, auto) == {
            "prefill": PREFILL, "decode": DECODE
        }
        assert registry.gauge("poolgroup", "degraded_total").get(
            "serving", "default"
        ) == 1.0

    def test_gauges_retire_when_the_group_is_deleted(self):
        store, registry, auto, _ = _world(
            groups=[_serving_group(REPAIR_BAND)]
        )
        _tick(store, auto)
        gauge = registry.gauge("poolgroup", "ratio_ok")
        assert gauge.get("serving", "default") == 1.0
        store.delete("PoolGroup", "default", "serving")
        _tick(store, auto)
        assert gauge.get("serving", "default") is None
        assert registry.gauge("poolgroup", "expected_hourly").get(
            "serving", "default"
        ) is None

    def test_headroom_feeds_the_warm_pool_signal(self):
        store, _, auto, engine = _world(
            groups=[_serving_group(REPAIR_BAND)]
        )
        _tick(store, auto)
        assert engine.headroom("default", "g-decode") >= 0
        assert engine.headroom("default", "nope") == 0


class TestGroupAwareAdmission:
    def test_grouped_tenants_ride_one_round(self):
        from karpenter_tpu.tenancy.fairness import WeightedAdmission

        adm = WeightedAdmission(budget_rows=100)
        schedule = adm.rounds(
            {"a": 60, "b": 60, "c": 10}, {},
            {"a": "pg1", "b": "pg1"},
        )
        for admitted in schedule:
            assert ("a" in admitted) == ("b" in admitted), (
                "coalition members split across rounds"
            )
        assert any({"a", "b"} <= set(r) for r in schedule)

    def test_ungrouped_schedule_is_unchanged(self):
        from karpenter_tpu.tenancy.fairness import WeightedAdmission

        demand = {"a": 30, "b": 50, "c": 40}
        weights = {"a": 2.0, "b": 1.0, "c": 1.0}
        plain = WeightedAdmission(budget_rows=64)
        grouped = WeightedAdmission(budget_rows=64)
        assert plain.rounds(demand, weights) == grouped.rounds(
            demand, weights, {}
        )

    def test_registry_exposes_pool_groups(self):
        from karpenter_tpu.tenancy.registry import (
            TenantRegistry,
            TenantSpec,
        )

        registry = TenantRegistry(specs=[
            TenantSpec(id="t1", pool_group="serving"),
            TenantSpec(id="t2", pool_group="serving"),
            TenantSpec(id="t3"),
        ])
        assert registry.pool_groups() == {
            "t1": "serving", "t2": "serving"
        }
        with pytest.raises(ValueError, match="poolGroup"):
            TenantSpec(id="bad", pool_group="").validate()


# -- the regression guard (bench-poolgroup published) --------------------------


class TestPoolGroupRegressionGuard:
    def test_published_dispatch_collapse_floor(self):
        """Published bench-poolgroup rows keep the one-batched-dispatch
        plane ahead of the per-pool dispatches it replaces, with both
        parity pins intact and the dispatch shapes honest."""
        import json
        import os

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BASELINE.json",
        )
        with open(path) as f:
            published = json.load(f).get("published", {})
        records = {
            k: v for k, v in published.items()
            if " joint allocation (" in k
        }
        if not records:
            pytest.skip(
                "no poolgroup record in BASELINE.json — run "
                "`make bench-poolgroup`"
            )
        for key, rec in records.items():
            assert rec["parity"] == "bitwise", key
            assert rec["dispatches_joint"] == 1, key
            assert (
                rec["dispatches_per_pool"] == rec["groups"] * rec["pools"]
            ), key
            assert rec["speedup"] >= 1.2, (
                f"{key}: joint-dispatch speedup regressed to "
                f"{rec['speedup']}x"
            )

    def test_live_joint_not_slower_than_per_pool(self):
        """The live guard: one warmed joint dispatch must not fall
        behind the warmed per-pool loop it replaces (generous margin —
        this catches a dispatch-collapse regression, not timer noise)."""
        import time

        import jax

        from bench import build_poolgroup_inputs

        inputs = build_poolgroup_inputs(16, 3, 2, seed=7)
        rows = []
        G, P = 16, 3
        for i in range(G * P):
            g, p = divmod(i, P)
            rows.append(CK.CostInputs(
                base_desired=inputs.base_desired[g, p: p + 1],
                min_replicas=inputs.min_replicas[g, p: p + 1],
                max_replicas=inputs.max_replicas[g, p: p + 1],
                unit_cost=inputs.unit_cost[g, p: p + 1],
                slo_weight=inputs.slo_weight[g, p: p + 1],
                max_hourly_cost=inputs.max_hourly_cost[g, p: p + 1],
                slo_valid=inputs.pool_valid[g, p: p + 1],
                slo_target=inputs.slo_target[g, p: p + 1],
                demand_mu=inputs.demand_mu[g, p: p + 1],
                demand_sigma=inputs.demand_sigma[g, p: p + 1],
                demand_valid=inputs.demand_valid[g, p: p + 1],
            ))
        jax.block_until_ready(PGK.poolgroup_jit(inputs))  # warm
        jax.block_until_ready(CK.cost_jit(rows[0]))       # warm

        def p50(fn, iters=5):
            times = []
            for _ in range(iters):
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
            return sorted(times)[len(times) // 2]

        joint = p50(
            lambda: jax.block_until_ready(PGK.poolgroup_jit(inputs))
        )
        loop = p50(lambda: [
            jax.block_until_ready(CK.cost_jit(r)) for r in rows
        ])
        assert joint <= loop * 1.5, (
            f"one joint dispatch ({joint * 1e3:.2f}ms) fell behind the "
            f"{G * P}-dispatch per-pool loop ({loop * 1e3:.2f}ms)"
        )
