"""API-layer behavior: behavior defaults/merge, select policy, stabilization,
validation rules, condition management."""

import pytest

from karpenter_tpu.api import conditions
from karpenter_tpu.api.core import (
    Container,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    Pod,
    PodSpec,
    is_ready_and_schedulable,
    resource_list,
)
from karpenter_tpu.api.horizontalautoscaler import (
    Behavior,
    DISABLED_POLICY_SELECT,
    HorizontalAutoscaler,
    MAX_POLICY_SELECT,
    MIN_POLICY_SELECT,
    ScalingRules,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    Pattern,
    ReservedCapacitySpec,
    ScheduleSpec,
    ScheduledBehavior,
)
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
    register_scalable_node_group_validator,
)


class TestBehavior:
    """reference: horizontalautoscaler.go:226-275"""

    def test_default_up_rules(self):
        rules = Behavior().scale_up_rules()
        assert rules.stabilization_window_seconds == 0
        assert rules.select_policy == MAX_POLICY_SELECT

    def test_default_down_rules(self):
        rules = Behavior().scale_down_rules()
        assert rules.stabilization_window_seconds == 300
        assert rules.select_policy == MAX_POLICY_SELECT

    def test_user_rules_merge_over_defaults(self):
        b = Behavior(scale_down=ScalingRules(stabilization_window_seconds=60))
        rules = b.scale_down_rules()
        assert rules.stabilization_window_seconds == 60
        assert rules.select_policy == MAX_POLICY_SELECT  # default survives

    def test_direction_picks_rules(self):
        b = Behavior()
        assert b.get_scaling_rules(5, [8]).stabilization_window_seconds == 0
        assert b.get_scaling_rules(5, [3]).stabilization_window_seconds == 300
        assert b.get_scaling_rules(5, [5]).select_policy == DISABLED_POLICY_SELECT

    def test_select_policy_max_min_disabled(self):
        assert Behavior().apply_select_policy(5, [3, 8]) == 8
        b_min = Behavior(scale_up=ScalingRules(select_policy=MIN_POLICY_SELECT))
        assert b_min.apply_select_policy(5, [6, 8]) == 6
        b_off = Behavior(scale_up=ScalingRules(select_policy=DISABLED_POLICY_SELECT))
        assert b_off.apply_select_policy(5, [6, 8]) == 5

    def test_stabilization_window(self):
        rules = ScalingRules(stabilization_window_seconds=300)
        assert rules.within_stabilization_window(1000.0, now=1100.0)
        assert not rules.within_stabilization_window(1000.0, now=1301.0)
        assert not rules.within_stabilization_window(None, now=1100.0)
        assert not ScalingRules().within_stabilization_window(1000.0, now=1001.0)


class TestValidation:
    def test_ha_max_lt_min_rejected(self):
        ha = HorizontalAutoscaler()
        ha.spec.min_replicas, ha.spec.max_replicas = 5, 3
        with pytest.raises(ValueError):
            ha.validate()

    def test_scaling_policy_bounds(self):
        """reference: horizontalautoscaler.go:137-146 — documented bounds
        the reference never enforces (value > 0, 0 < periodSeconds <= 1800)."""
        from karpenter_tpu.api.horizontalautoscaler import ScalingPolicy

        ScalingPolicy(type="Count", value=4, period_seconds=60).validate()
        ScalingPolicy(type="Percent", value=100, period_seconds=1800).validate()
        for bad in (
            ScalingPolicy(type="Pods", value=4, period_seconds=60),
            ScalingPolicy(type="Count", value=0, period_seconds=60),
            ScalingPolicy(type="Count", value=-1, period_seconds=60),
            ScalingPolicy(type="Count", value=4, period_seconds=0),
            ScalingPolicy(type="Count", value=4, period_seconds=1801),
        ):
            with pytest.raises(ValueError):
                bad.validate()

    def test_ha_validates_nested_policies(self):
        from karpenter_tpu.api.horizontalautoscaler import ScalingPolicy

        ha = HorizontalAutoscaler()
        ha.spec.max_replicas = 10
        ha.spec.behavior = Behavior(
            scale_up=ScalingRules(
                policies=[
                    ScalingPolicy(type="Count", value=4, period_seconds=2000)
                ]
            )
        )
        with pytest.raises(ValueError, match="periodSeconds"):
            ha.validate()

    def test_reserved_capacity_selector_cardinality(self):
        """reference: metricsproducer_validation.go:90-95"""
        with pytest.raises(ValueError):
            ReservedCapacitySpec(node_selector={}).validate()
        with pytest.raises(ValueError):
            ReservedCapacitySpec(node_selector={"a": "1", "b": "2"}).validate()
        ReservedCapacitySpec(node_selector={"a": "1"}).validate()

    @pytest.mark.parametrize(
        "pattern",
        [
            Pattern(weekdays="fri", hours="17"),
            Pattern(weekdays="Sunday,mon"),
            Pattern(months="jan,February,3"),
            Pattern(days="1,15", minutes="30"),
        ],
    )
    def test_valid_patterns(self, pattern):
        pattern.validate()

    @pytest.mark.parametrize(
        "pattern",
        [
            Pattern(weekdays="blursday"),
            Pattern(months="13"),
            Pattern(hours="noon"),
            Pattern(minutes="-5"),
            # out-of-range numerics must fail admission, not reconcile
            Pattern(hours="25"),
            Pattern(days="0"),
            Pattern(days="32"),
            Pattern(minutes="75"),
        ],
    )
    def test_invalid_patterns(self, pattern):
        with pytest.raises(ValueError):
            pattern.validate()

    def test_validated_pattern_always_compiles_to_cron(self):
        p = Pattern(weekdays="Sunday,mon", months="jan,February,3", hours="23")
        p.validate()
        p.to_cron()  # must not raise: admission and engine agree

    def test_schedule_spec_validation(self):
        """reference: metricsproducer_validation.go:61-82"""
        good = ScheduleSpec(
            behaviors=[
                ScheduledBehavior(
                    replicas=2,
                    start=Pattern(weekdays="fri"),
                    end=Pattern(weekdays="mon"),
                )
            ],
            timezone="America/Los_Angeles",
            default_replicas=1,
        )
        good.validate()
        bad_tz = ScheduleSpec(timezone="Mars/Olympus", default_replicas=1)
        with pytest.raises(ValueError, match="timezone"):
            bad_tz.validate()
        bad_replicas = ScheduleSpec(default_replicas=-1)
        with pytest.raises(ValueError, match="defaultReplicas"):
            bad_replicas.validate()

    def test_sng_validator_registry(self):
        """reference: scalablenodegroup_validation.go:39-56"""
        sng = ScalableNodeGroup(
            spec=ScalableNodeGroupSpec(type="TestGroupKind", id="x")
        )
        with pytest.raises(ValueError, match="Unexpected type"):
            sng.validate()
        register_scalable_node_group_validator("TestGroupKind", lambda spec: None)
        sng.validate()


class TestConditions:
    def test_living_set_ready_rollup(self):
        ha = HorizontalAutoscaler()
        mgr = ha.status_conditions()
        mgr.initialize()
        assert not mgr.is_happy()
        for t in (conditions.ACTIVE, conditions.ABLE_TO_SCALE, conditions.SCALING_UNBOUNDED):
            mgr.mark_true(t)
        assert mgr.is_happy()
        assert mgr.get(conditions.READY).status == conditions.TRUE

        mgr.mark_false(conditions.ABLE_TO_SCALE, "", "within stabilization window")
        assert not mgr.is_happy()
        assert mgr.get(conditions.READY).status == conditions.FALSE
        assert "stabilization" in mgr.get(conditions.READY).message

    def test_conditions_persist_on_resource(self):
        mp = MetricsProducer()
        mp.status_conditions().mark_true(conditions.ACTIVE)
        assert mp.status_conditions().is_happy()


class TestCoreObjects:
    def test_node_readiness_predicate(self):
        """reference: pkg/utils/node/predicates.go:18-25"""
        ready = Node(status=NodeStatus(conditions=[NodeCondition("Ready", "True")]))
        assert is_ready_and_schedulable(ready)
        not_ready = Node(
            status=NodeStatus(conditions=[NodeCondition("Ready", "False")])
        )
        assert not is_ready_and_schedulable(not_ready)
        cordoned = Node(
            spec=NodeSpec(unschedulable=True),
            status=NodeStatus(conditions=[NodeCondition("Ready", "True")]),
        )
        assert not is_ready_and_schedulable(cordoned)
        no_conditions = Node()
        assert not is_ready_and_schedulable(no_conditions)

    def test_pod_request_totals(self):
        pod = Pod(
            spec=PodSpec(
                containers=[
                    Container(requests=resource_list(cpu="500m", memory="1Gi")),
                    Container(requests=resource_list(cpu="250m")),
                ]
            )
        )
        totals = pod.requests()
        assert str(totals["cpu"]) == "750m"
        assert str(totals["memory"]) == "1Gi"

    def test_pod_effective_requests(self):
        """Scheduler fit semantics: per resource
        max(container sum, init-container max) + overhead; requests()
        stays container-sum (the reference's reserved-capacity
        accounting, reservations.go:45-56)."""
        pod = Pod(
            spec=PodSpec(
                containers=[
                    Container(requests=resource_list(cpu="500m", memory="1Gi")),
                    Container(requests=resource_list(cpu="250m")),
                ],
                init_containers=[
                    # cpu below the main-phase sum: main phase wins
                    Container(requests=resource_list(cpu="600m")),
                    # memory above it: init phase wins for memory
                    Container(requests=resource_list(memory="4Gi")),
                    # a resource only the init phase requests
                    Container(requests=resource_list(**{"ephemeral-storage": "2Gi"})),
                ],
                overhead=resource_list(cpu="100m", memory="64Mi"),
            )
        )
        eff = pod.effective_requests()
        assert str(eff["cpu"]) == "850m"  # max(750m, 600m) + 100m
        assert eff["memory"].to_float() == pytest.approx(
            4 * 1024**3 + 64 * 1024**2
        )  # max(1Gi, 4Gi) + 64Mi
        assert str(eff["ephemeral-storage"]) == "2Gi"
        # the reference-parity accounting is untouched by init/overhead
        totals = pod.requests()
        assert str(totals["cpu"]) == "750m"
        assert str(totals["memory"]) == "1Gi"
        assert "ephemeral-storage" not in totals

    def test_affinity_requirement_operators(self):
        from karpenter_tpu.api.core import _requirement_matches as m

        labels = {"zone": "us-east1-a", "tier": "3", "arch": "arm64"}
        assert m(labels, "zone", "In", ("us-east1-a", "us-east1-b"))
        assert not m(labels, "zone", "In", ("us-west1-a",))
        assert not m(labels, "missing", "In", ("x",))
        assert m(labels, "zone", "NotIn", ("us-west1-a",))
        assert not m(labels, "zone", "NotIn", ("us-east1-a",))
        assert m(labels, "missing", "NotIn", ("x",))  # absent satisfies NotIn
        assert m(labels, "arch", "Exists", ())
        assert not m(labels, "missing", "Exists", ())
        assert m(labels, "missing", "DoesNotExist", ())
        assert not m(labels, "arch", "DoesNotExist", ())
        assert m(labels, "tier", "Gt", ("2",))
        assert not m(labels, "tier", "Gt", ("3",))
        assert m(labels, "tier", "Lt", ("4",))
        assert not m(labels, "missing", "Gt", ("1",))
        assert not m(labels, "arch", "Gt", ("1",))  # non-integer value
        assert not m(labels, "tier", "Bogus", ("1",))  # unknown operator

    def test_affinity_shape_and_matching(self):
        from karpenter_tpu.api.core import (
            Affinity,
            NodeAffinity,
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            affinity_shape,
            matches_affinity_shape,
        )

        affinity = Affinity(
            node_affinity=NodeAffinity(
                required_during_scheduling_ignored_during_execution=NodeSelector(
                    node_selector_terms=[
                        NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(
                                    key="zone", operator="In",
                                    values=["a", "b"],
                                ),
                                NodeSelectorRequirement(
                                    key="gpu", operator="DoesNotExist",
                                ),
                            ]
                        ),
                        NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(
                                    key="tier", operator="Exists",
                                )
                            ]
                        ),
                    ]
                )
            )
        )
        shape = affinity_shape(affinity)
        # term 1: zone in {a,b} AND no gpu label; term 2 (OR): tier exists
        assert matches_affinity_shape({"zone": "a"}, shape)
        assert not matches_affinity_shape({"zone": "a", "gpu": "1"}, shape)
        assert matches_affinity_shape({"gpu": "1", "tier": "x"}, shape)
        assert not matches_affinity_shape({"zone": "c"}, shape)
        # empty/None affinity is unconstrained
        assert affinity_shape(None) == ()
        assert affinity_shape(Affinity()) == ()
        assert matches_affinity_shape({}, ())
        # an empty term matches nothing (k8s nodeaffinity helpers), so an
        # affinity of ONLY empty terms matches nothing
        empty_term = affinity_shape(
            Affinity(
                node_affinity=NodeAffinity(
                    required_during_scheduling_ignored_during_execution=(
                        NodeSelector(node_selector_terms=[NodeSelectorTerm()])
                    )
                )
            )
        )
        assert not matches_affinity_shape({"zone": "a"}, empty_term)

    def test_preferred_shape_and_scoring(self):
        from karpenter_tpu.api.core import (
            Affinity,
            NodeAffinity,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            PreferredSchedulingTerm,
            preference_score,
            preferred_shape,
        )

        affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred_during_scheduling_ignored_during_execution=[
                    PreferredSchedulingTerm(
                        weight=80,
                        preference=NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(
                                    key="disk", operator="In", values=["ssd"]
                                )
                            ]
                        ),
                    ),
                    PreferredSchedulingTerm(
                        weight=20,
                        preference=NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(
                                    key="zone", operator="In", values=["a"]
                                )
                            ]
                        ),
                    ),
                    # empty preference term: can never match, dropped
                    PreferredSchedulingTerm(weight=100),
                ]
            )
        )
        shape = preferred_shape(affinity)
        assert len(shape) == 2
        assert preference_score({"disk": "ssd", "zone": "a"}, shape) == 100
        assert preference_score({"disk": "ssd"}, shape) == 80
        assert preference_score({"zone": "a"}, shape) == 20
        assert preference_score({}, shape) == 0
        assert preferred_shape(None) == ()
        assert preferred_shape(Affinity()) == ()

    def test_pod_effective_requests_no_init_no_overhead(self):
        pod = Pod(
            spec=PodSpec(
                containers=[Container(requests=resource_list(cpu="1"))]
            )
        )
        assert {k: str(v) for k, v in pod.effective_requests().items()} == {
            k: str(v) for k, v in pod.requests().items()
        }
