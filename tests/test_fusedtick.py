"""Fused steady-state tick (ops/fusedtick.py + SolverService.fused_tick).

The ISSUE acceptance pins, in suite form:

  * property pin — fused megakernel == chained forecast -> decide ->
    cost wire == numpy mirror, BITWISE, on the device and numpy service
    paths, across every stage-presence combination;
  * masked-operand contract — an all-masked forecast/SLO group is
    byte-identical to the absent-operand wire (the PR 16 posture);
  * per-tenant batch slices — a tenant's slice of the shared fused
    dispatch equals its own independent fused dispatch, bit for bit;
  * runtime fixed point — --fused-tick on/off produce the same replica
    trail while the dispatches-per-tick gauge collapses 3+ -> 1;
  * compile-cache restart — Options.compile_cache_dir persists the
    fused program; a rebooted service prewarns from disk with ZERO
    fresh compile-ledger rows;
  * regression guard — fused must not get slower than the chained wire
    (live, non-slow) and published bench-fusedtick rows keep their
    speedup floor.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np
import pytest

from karpenter_tpu.forecast import models as FM
from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.ops import decision as D
from karpenter_tpu.ops import fusedtick as FT
from karpenter_tpu.solver.service import SolverService

# -- seeded operand builders --------------------------------------------------


def mk_decision(seed, n, m, now=1000.0):
    r = np.random.RandomState(seed)
    k = 2
    return D.DecisionInputs(
        metric_value=r.uniform(0, 100, (n, m)).astype(np.float32),
        target_value=r.uniform(1, 80, (n, m)).astype(np.float32),
        target_type=r.randint(0, 3, (n, m)).astype(np.int32),
        metric_valid=r.rand(n, m) > 0.2,
        spec_replicas=r.randint(1, 20, n).astype(np.int32),
        status_replicas=r.randint(1, 20, n).astype(np.int32),
        min_replicas=r.randint(0, 3, n).astype(np.int32),
        max_replicas=r.randint(20, 40, n).astype(np.int32),
        up_window=r.randint(0, 60, n).astype(np.int32),
        down_window=r.randint(0, 120, n).astype(np.int32),
        up_policy=r.randint(0, 2, n).astype(np.int32),
        down_policy=r.randint(0, 2, n).astype(np.int32),
        last_scale_time=(now - r.uniform(0, 300, n)).astype(np.float32),
        has_last_scale=r.rand(n) > 0.3,
        now=np.float32(now),
        up_ptype=r.randint(0, 3, (n, k)).astype(np.int32),
        up_pvalue=r.randint(1, 10, (n, k)).astype(np.int32),
        up_pperiod=r.randint(15, 120, (n, k)).astype(np.int32),
        up_pvalid=r.rand(n, k) > 0.4,
        down_ptype=r.randint(0, 3, (n, k)).astype(np.int32),
        down_pvalue=r.randint(1, 10, (n, k)).astype(np.int32),
        down_pperiod=r.randint(15, 120, (n, k)).astype(np.int32),
        down_pvalid=r.rand(n, k) > 0.4,
    )


def mk_forecast_group(seed, s, t, n, m):
    r = np.random.RandomState(seed + 1)
    return dict(
        forecast=FM.ForecastInputs(
            values=r.uniform(0, 100, (s, t)).astype(np.float32),
            valid=r.rand(s, t) > 0.2,
            times=np.cumsum(r.uniform(10, 20, (s, t)), 1).astype(
                np.float32
            ),
            weights=np.ones((s, t), np.float32),
            horizon=np.full(s, 60.0, np.float32),
            step_s=np.full(s, 15.0, np.float32),
            model=r.randint(0, 2, s).astype(np.int32),
            season=np.full(s, 4, np.int32),
            alpha=np.full(s, 0.5, np.float32),
            beta=np.full(s, 0.1, np.float32),
            gamma=np.full(s, 0.1, np.float32),
        ),
        series_row=r.randint(0, n, s).astype(np.int32),
        series_col=r.randint(0, m, s).astype(np.int32),
        series_need=np.full(s, 2, np.int32),
        series_blend=r.rand(s) > 0.3,
    )


def mk_cost_group(seed, n, m):
    r = np.random.RandomState(seed + 2)
    return dict(
        ha_min=r.randint(0, 3, n).astype(np.int32),
        ha_max=r.randint(20, 40, n).astype(np.int32),
        unit_cost=r.uniform(0.1, 3.0, n).astype(np.float32),
        slo_weight=r.uniform(0, 2, n).astype(np.float32),
        max_hourly_cost=r.uniform(5, 50, n).astype(np.float32),
        slo_valid=r.rand(n) > 0.4,
        slo_target=r.uniform(1, 80, (n, m)).astype(np.float32),
        observed=r.uniform(0, 100, (n, m)).astype(np.float32),
        demand_base_valid=r.rand(n, m) > 0.3,
        prior_point=r.uniform(0, 100, (n, m)).astype(np.float32),
        prior_sigma2=r.uniform(0, 10, (n, m)).astype(np.float32),
        prior_valid=r.rand(n, m) > 0.5,
    )


def mk_inputs(seed, n, m, s=0, t=0, forecast=True, cost=True, now=1000.0):
    kwargs = dict(decision=mk_decision(seed, n, m, now=now))
    if forecast:
        kwargs.update(mk_forecast_group(seed, s, t, n, m))
    if cost:
        kwargs.update(mk_cost_group(seed, n, m))
    return FT.FusedTickInputs(**kwargs)


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, tree)
    )


def assert_bitwise(a, b, context=""):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb), f"{context}: leaf count {len(la)}!={len(lb)}"
    for i, (x, y) in enumerate(zip(la, lb)):
        assert x.dtype == y.dtype and x.shape == y.shape, (
            f"{context}: leaf {i} {x.dtype}{x.shape} vs {y.dtype}{y.shape}"
        )
        assert x.tobytes() == y.tobytes(), (
            f"{context}: leaf {i} differs bitwise"
        )


# -- the property pin: fused == chained == numpy, bitwise --------------------


class TestFusedKernelParity:
    PRESENCE = [
        (True, True), (True, False), (False, True), (False, False)
    ]

    def test_fused_chained_numpy_bitwise(self):
        """The tentpole contract: ONE compiled program returns exactly
        the bytes the chained per-stage wire returns, which returns
        exactly the bytes the numpy mirror returns — every presence
        combination, several seeds."""
        for has_forecast, has_cost in self.PRESENCE:
            for seed in (0, 1, 2):
                inputs = mk_inputs(
                    seed, n=16, m=2, s=12, t=10,
                    forecast=has_forecast, cost=has_cost,
                )
                ctx = f"f={has_forecast} c={has_cost} seed={seed}"
                fused = FT.fused_tick_jit(inputs)
                chained = FT.fused_tick_chained(inputs)
                mirror = FT.fused_tick_numpy(inputs)
                assert_bitwise(fused, chained, f"fused/chained {ctx}")
                assert_bitwise(fused, mirror, f"fused/numpy {ctx}")
                assert (fused.forecast is None) == (not has_forecast)
                assert (fused.cost is None) == (not has_cost)

    def test_masked_forecast_rows_match_absent_wire(self):
        """An all-masked forecast group (the tenancy concat's pad-row
        mask: blend gate False + an unreachable sample need) is
        byte-identical to the absent-forecast dispatch on the decision
        and cost planes — the PR 16 masked-operand contract carried
        into the megakernel."""
        base = mk_inputs(seed=5, n=12, m=2, s=9, t=8)
        masked = dataclasses.replace(
            base,
            series_blend=np.zeros(9, bool),
            series_need=np.full(9, np.iinfo(np.int32).max, np.int32),
        )
        absent = dataclasses.replace(
            base, forecast=None, series_row=None, series_col=None,
            series_need=None, series_blend=None,
        )
        out_masked = FT.fused_tick_jit(masked)
        out_absent = FT.fused_tick_jit(absent)
        assert_bitwise(
            out_masked.decision, out_absent.decision, "decision"
        )
        assert_bitwise(out_masked.cost, out_absent.cost, "cost")
        assert_bitwise(
            FT.fused_tick_numpy(masked).decision,
            out_absent.decision, "numpy decision",
        )
        # a blend-gate-only mask still feeds the cost stage's demand
        # distribution (the skill gate governs the decide blend alone)
        # but must leave the DECISION plane absent-identical
        blend_only = dataclasses.replace(
            base, series_blend=np.zeros(9, bool)
        )
        assert_bitwise(
            FT.fused_tick_jit(blend_only).decision,
            out_absent.decision, "blend-only decision",
        )

    def test_masked_slo_rows_match_absent_wire(self):
        """An all-masked cost group (every slo_valid False) passes the
        blended decision through untouched and leaves the decision +
        forecast planes byte-identical to the absent-SLO dispatch."""
        base = mk_inputs(seed=6, n=12, m=2, s=9, t=8)
        masked = dataclasses.replace(
            base, slo_valid=np.zeros(12, bool)
        )
        absent = FT.FusedTickInputs(
            decision=base.decision, forecast=base.forecast,
            series_row=base.series_row, series_col=base.series_col,
            series_need=base.series_need, series_blend=base.series_blend,
        )
        out_masked = FT.fused_tick_jit(masked)
        out_absent = FT.fused_tick_jit(absent)
        assert out_masked.cost is not None and out_absent.cost is None
        assert_bitwise(
            out_masked.decision, out_absent.decision, "decision"
        )
        assert_bitwise(
            out_masked.forecast, out_absent.forecast, "forecast"
        )
        # pass-through: the masked ladder never moves the blended base
        assert (
            np.asarray(out_masked.cost.desired).tobytes()
            == np.asarray(out_masked.decision.desired).tobytes()
        )

    def test_programs_counts_the_chained_wire(self):
        full = mk_inputs(0, n=8, m=2, s=4, t=6)
        assert FT.programs(full) == 3
        assert FT.programs(
            dataclasses.replace(full, slo_valid=None)
        ) == 2
        assert FT.programs(
            FT.FusedTickInputs(decision=full.decision)
        ) == 1


# -- the service seam ---------------------------------------------------------


class TestFusedServiceSeam:
    def _service(self, **kw):
        kw.setdefault("registry", GaugeRegistry())
        kw.setdefault("backend", "xla")
        return SolverService(**kw)

    def test_device_and_numpy_paths_bitwise(self):
        service = self._service()
        try:
            inputs = mk_inputs(7, n=10, m=2, s=6, t=8)
            device = service.fused_tick(inputs)
            host = service.fused_tick(inputs, backend="numpy")
            assert_bitwise(device, host, "device/numpy service paths")
            assert service.stats.fused_calls == 2
            assert service.stats.fused_dispatches == 1
            assert service.stats.fused_chained_serves == 0
            # an EXPLICIT numpy request is not a degraded serve
            assert service.stats.fused_mirror_serves == 0
        finally:
            service.close()

    def test_forecast_sliced_back_to_caller_s(self):
        """The door pads S up the forecast shape ladder; the caller
        gets exactly its own series back (padding rows are
        service-internal, like the queue family's)."""
        service = self._service()
        try:
            inputs = mk_inputs(8, n=10, m=2, s=5, t=8)
            out = service.fused_tick(inputs)
            assert np.asarray(out.forecast.point).shape[0] == 5
            assert np.asarray(out.forecast.sigma2).shape[0] == 5
        finally:
            service.close()

    def test_note_tick_collapses_gauge_to_one(self):
        """The dispatches-per-tick observable: a fused tick pays ONE
        device program where the chained wire pays one per stage."""
        service = self._service()
        try:
            inputs = mk_inputs(9, n=10, m=2, s=6, t=8)
            service.fused_tick(inputs)
            service.note_tick()
            assert service.stats.last_dispatches_per_tick == 1
            gauge = service.registry.gauge(
                "solver", "dispatches_per_tick"
            )
            assert gauge.get("-", "-") == 1.0
        finally:
            service.close()

    def test_prewarm_fused_family(self):
        service = self._service()
        try:
            service.reset_caches()  # order-independence: re-arm fused
            report = service.prewarm(("fused",))
            assert report["fused"]["skipped"] is False
            assert report["fused"]["fresh_compiles"] == 1
            assert service.stats.fused_dispatches == 1
            again = service.prewarm(("fused",))
            assert again["fused"] == {"skipped": True}
        finally:
            service.close()


# -- per-tenant batch slices --------------------------------------------------


class TestFusedTenancySlices:
    def test_shared_dispatch_slices_match_isolated(self):
        """Four tenants with mixed stage presence concatenated into ONE
        fused dispatch: every tenant's slice is byte-identical to its
        own isolated service.fused_tick, and the group really shares a
        single fused program."""
        from karpenter_tpu.tenancy import (
            MultiTenantScheduler,
            TenantRegistry,
            TenantSpec,
        )

        shapes = [
            # (seed, n, m, forecast, cost)
            (11, 12, 3, True, True),
            (12, 7, 2, True, False),
            (13, 9, 3, False, True),
            (14, 5, 1, False, False),
        ]
        batch = {
            f"t{i}": mk_inputs(
                seed, n=n, m=m, s=max(2, n // 2), t=8,
                forecast=fc, cost=cc,
            )
            for i, (seed, n, m, fc, cc) in enumerate(shapes)
        }
        shared = SolverService(registry=GaugeRegistry(), backend="xla")
        isolated = SolverService(
            registry=GaugeRegistry(), backend="xla"
        )
        try:
            registry = TenantRegistry(
                service=shared, registry=GaugeRegistry(),
                specs=[TenantSpec(id=t) for t in batch],
            )
            scheduler = MultiTenantScheduler(registry, shared)
            results = scheduler.fused_tick_all(batch)
            assert set(results) == set(batch)
            for tenant, inputs in batch.items():
                assert_bitwise(
                    results[tenant],
                    isolated.fused_tick(inputs),
                    f"tenant {tenant}",
                )
            assert scheduler.stats.fused_calls == 1
            # the mixed batch concatenates into TWO shared dispatches:
            # forecast-carrying tenants share one t-bucket group,
            # forecast-less tenants the other (grouping by forecast
            # time bucket keeps the T padding bit-preserving)
            assert scheduler.stats.fused_dispatches == 2
            assert shared.stats.fused_dispatches == 2

            # a homogeneous-forecast batch (cost presence still mixed
            # — absent tenants ride as all-masked rows) really shares
            # ONE fused program
            uniform = {
                f"u{i}": mk_inputs(
                    30 + i, n=6 + i, m=2, s=4, t=8,
                    forecast=True, cost=(i % 2 == 0),
                )
                for i in range(4)
            }
            registry2 = TenantRegistry(
                service=shared, registry=GaugeRegistry(),
                specs=[TenantSpec(id=t) for t in uniform],
            )
            scheduler2 = MultiTenantScheduler(registry2, shared)
            before = shared.stats.fused_dispatches
            results2 = scheduler2.fused_tick_all(uniform)
            assert shared.stats.fused_dispatches == before + 1
            for tenant, inputs in uniform.items():
                assert_bitwise(
                    results2[tenant],
                    isolated.fused_tick(inputs),
                    f"tenant {tenant}",
                )
        finally:
            shared.close()
            isolated.close()


# -- the runtime fixed point: fused on == fused off ---------------------------


def _decision_world(**options_kw):
    """A seeded runtime whose every tick exercises decide + forecast +
    cost (the test_provenance world): the full fused-stage surface."""
    from karpenter_tpu.api.core import ObjectMeta
    from karpenter_tpu.api.horizontalautoscaler import (
        Behavior,
        CrossVersionObjectReference,
        ForecastSpec,
        HorizontalAutoscaler,
        HorizontalAutoscalerSpec,
        Metric,
        MetricTarget,
        PrometheusMetricSource,
        ScalingRules,
        SLOSpec,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup,
        ScalableNodeGroupSpec,
    )
    from karpenter_tpu.cloudprovider.fake import FakeFactory
    from karpenter_tpu.runtime import KarpenterRuntime, Options

    clock = {"now": 1_000_000.0}
    provider = FakeFactory()
    provider.node_replicas["g"] = 2
    runtime = KarpenterRuntime(
        Options(**options_kw), cloud_provider_factory=provider,
        clock=lambda: clock["now"],
    )
    # the fused/chained device paths both need the compiled backend:
    # "auto" resolves to numpy on the CPU test backend (bit-parity
    # keeps the decisions identical either way; the dispatch-count
    # observable needs the device rung)
    runtime.solver_service.backend = "xla"
    runtime.store.create(ScalableNodeGroup(
        metadata=ObjectMeta(name="g"),
        spec=ScalableNodeGroupSpec(
            replicas=2, type="FakeNodeGroup", id="g"
        ),
    ))
    runtime.store.create(HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name="g"
            ),
            min_replicas=1, max_replicas=50,
            metrics=[Metric(prometheus=PrometheusMetricSource(
                query='karpenter_queue_length{name="q"}',
                target=MetricTarget(type="AverageValue", value=4),
            ))],
            behavior=Behavior(
                scale_down=ScalingRules(
                    stabilization_window_seconds=0
                ),
                forecast=ForecastSpec(
                    horizon_seconds=30, min_samples=3, model="linear",
                ),
                slo=SLOSpec(
                    target_value=3.0, violation_cost_weight=25.0,
                ),
            ),
        ),
    ))
    gauge = runtime.registry.register("queue", "length")
    return runtime, provider, gauge, clock


def _run_world(ticks=12, **options_kw):
    runtime, provider, gauge, clock = _decision_world(**options_kw)
    trail = []
    try:
        for tick in range(ticks):
            gauge.set("q", "default", 8.0 + 3.0 * tick)
            runtime.manager._due = {k: 0.0 for k in runtime.manager._due}
            runtime.manager.reconcile_all()
            clock["now"] += 10.0
            trail.append(provider.node_replicas["g"])
        stats = dataclasses.replace(runtime.solver_service.stats)
    finally:
        runtime.close()
    return trail, stats


class TestFusedRuntimeFixedPoint:
    def test_fused_on_off_same_trail_one_program_per_tick(self):
        """--fused-tick keeps the replica trail byte-identical to the
        chained wire while the steady-state tick collapses to ONE
        device program (the dispatches-per-tick gauge delta the bench
        publishes)."""
        chained_trail, chained_stats = _run_world()
        fused_trail, fused_stats = _run_world(fused_tick=True)
        assert fused_trail == chained_trail, (
            "the fused tick observes the same math; it must never "
            "change a decision"
        )
        assert fused_stats.fused_calls > 0
        assert fused_stats.fused_dispatches > 0
        assert fused_stats.fused_chained_serves == 0
        assert fused_stats.fused_mirror_serves == 0
        # the headline observable: forecast + decide + cost engaged,
        # yet the last steady-state tick paid exactly one program —
        # while the chained wire pays one per engaged stage
        assert fused_stats.last_dispatches_per_tick == 1
        assert chained_stats.last_dispatches_per_tick >= 2
        assert chained_stats.fused_calls == 0

    def test_default_off_never_routes_fused(self):
        _, stats = _run_world(ticks=4)
        assert stats.fused_calls == 0
        assert stats.fused_dispatches == 0


# -- compile-cache restart: prewarm from disk, zero fresh ledger rows ---------


class TestCompileCacheRestart:
    def test_restart_prewarns_from_cache_zero_fresh_rows(self, tmp_path):
        """Options.compile_cache_dir (the --compile-cache-dir
        promotion of KARPENTER_COMPILE_CACHE): the first boot persists
        the fused program; a restarted service prewarns the fused
        family with ZERO fresh compile-ledger rows and writes nothing
        new to the cache."""
        import jax

        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime, Options

        old_dir = jax.config.jax_compilation_cache_dir
        old_min = jax.config.jax_persistent_cache_min_compile_time_secs
        try:
            runtime1 = KarpenterRuntime(
                Options(
                    fused_tick=True,
                    compile_cache_dir=str(tmp_path),
                ),
                cloud_provider_factory=FakeFactory(),
            )
            assert jax.config.jax_compilation_cache_dir == str(tmp_path)
            # CPU test compiles finish in <1s; the production threshold
            # (1s, set by configure_compile_cache) would persist none
            # of them — lower it so this test exercises the disk layer
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
            # "auto" resolves to the numpy floor on the CPU test
            # backend — the compile/persist layers need the jitted path
            runtime1.solver_service.backend = "xla"
            try:
                # force a genuinely fresh fused compile regardless of
                # what earlier tests warmed in this process
                runtime1.solver_service.reset_caches()
                jax.clear_caches()
                report1 = runtime1.solver_service.prewarm(("fused",))
                assert report1["fused"]["fresh_compiles"] == 1
            finally:
                runtime1.close()
            cached = sorted(p.name for p in tmp_path.iterdir())
            assert cached, (
                "the fused prewarm compile must persist to the cache dir"
            )

            # -- "restart": drop the in-process compiled programs; the
            # disk cache (and the process fused-seen keys) survive
            jax.clear_caches()
            runtime2 = KarpenterRuntime(
                Options(
                    fused_tick=True,
                    compile_cache_dir=str(tmp_path),
                    introspect=True,
                ),
                cloud_provider_factory=FakeFactory(),
            )
            runtime2.solver_service.backend = "xla"
            try:
                plane = runtime2.solver_introspection
                before = plane.ledger.records_total
                report2 = runtime2.solver_service.prewarm(("fused",))
                assert report2["fused"]["skipped"] is False
                assert report2["fused"]["fresh_compiles"] == 0, (
                    "a rebooted plane must prewarm from the persistent "
                    "cache, not pay the compile again"
                )
                assert "ms" in report2["fused"]
                assert plane.ledger.records_total == before
                assert plane.ledger.by_family.get("fused") is None
            finally:
                runtime2.close()
            assert sorted(p.name for p in tmp_path.iterdir()) == cached, (
                "the warm reboot must add no new cache entries"
            )
        finally:
            jax.config.update("jax_compilation_cache_dir", old_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", old_min
            )
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except (ImportError, AttributeError):
                pass

    def test_flag_wins_over_env(self, tmp_path, monkeypatch):
        """--compile-cache-dir beats KARPENTER_COMPILE_CACHE (the
        sidecar precedence), and the parser defaults keep the feature
        off."""
        from karpenter_tpu.__main__ import parse_args

        monkeypatch.setenv("KARPENTER_COMPILE_CACHE", "/env/dir")
        args = parse_args(["--compile-cache-dir", str(tmp_path)])
        assert args.compile_cache_dir == str(tmp_path)
        args = parse_args([])
        assert args.compile_cache_dir is None
        assert args.fused_tick is False  # default off

    def test_production_profile_enables_fused_tick(self):
        from karpenter_tpu.__main__ import parse_args

        args = parse_args(["--profile", "production"])
        assert args.fused_tick is True
        args = parse_args(["--profile", "production", "--no-fused-tick"])
        assert args.fused_tick is False


# -- the regression guard (bench-fusedtick published + live) ------------------


def _baseline():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BASELINE.json",
    )
    with open(path) as f:
        return json.load(f)


class TestFusedRegressionGuard:
    def test_published_speedup_floor(self):
        """Published bench-fusedtick rows keep the fused-vs-chained
        speedup above the regression floor with bitwise parity and the
        one-program dispatch shape."""
        published = _baseline().get("published", {})
        records = {
            k: v for k, v in published.items() if " fusedtick (" in k
        }
        if not records:
            pytest.skip(
                "no fusedtick record in BASELINE.json — run "
                "`make bench-fusedtick`"
            )
        for key, rec in records.items():
            assert rec["parity"] == "bitwise", key
            assert rec["speedup"] >= 1.1, (
                f"{key}: fused speedup regressed to {rec['speedup']}x"
            )
            assert rec["programs_fused"] == 1, key
            assert rec["programs_chained"] >= 3, key

    def test_live_fused_not_slower_than_chained(self):
        """The live guard: one warmed fused dispatch must not fall
        behind the warmed chained wire (generous margin — this catches
        a fusion regression, not timer noise)."""
        import jax

        inputs = mk_inputs(21, n=256, m=3, s=128, t=32)
        jax.block_until_ready(_leaves(FT.fused_tick_jit(inputs)))
        FT.fused_tick_chained(inputs)

        def best(fn, reps=3):
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
            return min(times)

        fused = best(
            lambda: jax.block_until_ready(
                _leaves(FT.fused_tick_jit(inputs))
            )
        )
        chained = best(lambda: FT.fused_tick_chained(inputs))
        assert fused < chained * 1.5, (
            f"fused tick {fused * 1e3:.3f}ms fell behind the chained "
            f"wire {chained * 1e3:.3f}ms"
        )
