"""Decision kernel golden + property tests.

Golden cases come from the reference's table tests
(pkg/autoscaler/algorithms/proportional_test.go:26-140) and suite
expectations (horizontalautoscaler/v1alpha1/suite_test.go:94-118). The
property test runs the full batched kernel against the scalar host pipeline
(api.Behavior + algorithms.Proportional), which mirrors
pkg/autoscaler/autoscaler.go:144-194 step by step.
"""

import numpy as np
import pytest

from karpenter_tpu.api.horizontalautoscaler import (
    AVERAGE_VALUE,
    Behavior,
    ScalingRules,
    UTILIZATION,
    VALUE,
)
from karpenter_tpu.autoscaler.algorithms import Metric, Proportional
from karpenter_tpu.ops import decision as D


def make_inputs(
    metric_value,
    target_value,
    target_type,
    metric_valid,
    spec_replicas,
    status_replicas,
    min_replicas,
    max_replicas,
    up_window=None,
    down_window=None,
    up_policy=None,
    down_policy=None,
    last_scale_time=None,
    has_last_scale=None,
    now=0.0,
    up_policies=None,
    down_policies=None,
):
    """up_policies/down_policies: per-row lists of (type, value, period)."""
    import jax.numpy as jnp

    n = len(spec_replicas)
    default = lambda v, fill: np.asarray(v if v is not None else [fill] * n)

    def slots(policy_lists):
        k = max([1] + [len(p or []) for p in (policy_lists or [])])
        ptype = np.zeros((n, k), np.int32)
        pvalue = np.zeros((n, k), np.int32)
        pperiod = np.ones((n, k), np.int32)
        pvalid = np.zeros((n, k), bool)
        for i, policies in enumerate(policy_lists or [[]] * n):
            for j, (t, v, p) in enumerate(policies or []):
                ptype[i, j], pvalue[i, j], pperiod[i, j] = t, v, p
                pvalid[i, j] = True
        return (
            jnp.asarray(ptype),
            jnp.asarray(pvalue),
            jnp.asarray(pperiod),
            jnp.asarray(pvalid),
        )

    up_ptype, up_pvalue, up_pperiod, up_pvalid = slots(up_policies)
    down_ptype, down_pvalue, down_pperiod, down_pvalid = slots(down_policies)
    return D.DecisionInputs(
        metric_value=jnp.asarray(np.asarray(metric_value, np.float32)),
        target_value=jnp.asarray(np.asarray(target_value, np.float32)),
        target_type=jnp.asarray(np.asarray(target_type, np.int32)),
        metric_valid=jnp.asarray(np.asarray(metric_valid, bool)),
        spec_replicas=jnp.asarray(np.asarray(spec_replicas, np.int32)),
        status_replicas=jnp.asarray(np.asarray(status_replicas, np.int32)),
        min_replicas=jnp.asarray(np.asarray(min_replicas, np.int32)),
        max_replicas=jnp.asarray(np.asarray(max_replicas, np.int32)),
        up_window=jnp.asarray(default(up_window, 0).astype(np.int32)),
        down_window=jnp.asarray(default(down_window, 300).astype(np.int32)),
        up_policy=jnp.asarray(default(up_policy, D.POLICY_MAX).astype(np.int32)),
        down_policy=jnp.asarray(default(down_policy, D.POLICY_MAX).astype(np.int32)),
        last_scale_time=jnp.asarray(default(last_scale_time, 0.0).astype(np.float32)),
        has_last_scale=jnp.asarray(default(has_last_scale, False).astype(bool)),
        now=jnp.float32(now),
        up_ptype=up_ptype,
        up_pvalue=up_pvalue,
        up_pperiod=up_pperiod,
        up_pvalid=up_pvalid,
        down_ptype=down_ptype,
        down_pvalue=down_pvalue,
        down_pperiod=down_pperiod,
        down_pvalid=down_pvalid,
    )


def single(metric_value, target_value, target_type, status_replicas, **kw):
    """One autoscaler, one metric, unbounded, no stabilization history."""
    defaults = dict(
        spec_replicas=[kw.pop("spec_replicas", status_replicas)],
        status_replicas=[status_replicas],
        min_replicas=[kw.pop("min_replicas", -(2**31))],
        max_replicas=[kw.pop("max_replicas", 2**31 - 1)],
    )
    return make_inputs(
        metric_value=[[metric_value]],
        target_value=[[target_value]],
        target_type=[[target_type]],
        metric_valid=[[True]],
        **defaults,
        **kw,
    )


class TestProportionalGolden:
    """reference: proportional_test.go:26-140 — both the scalar oracle and
    the device kernel must reproduce all seven cases."""

    CASES = [
        # (target_type_str, type_code, target, value, replicas, want)
        (VALUE, D.TYPE_VALUE, 3, 50, 8, 134),
        (VALUE, D.TYPE_VALUE, 3, 50, 0, 1),
        (AVERAGE_VALUE, D.TYPE_AVERAGE_VALUE, 50, 304, 1, 7),
        (AVERAGE_VALUE, D.TYPE_AVERAGE_VALUE, 50, 304, 0, 7),
        (UTILIZATION, D.TYPE_UTILIZATION, 50, 0.6, 2, 3),
        (UTILIZATION, D.TYPE_UTILIZATION, 50, 0.6, 0, 1),
        ("", D.TYPE_UNKNOWN, 0, 0, 50, 50),
    ]

    @pytest.mark.parametrize("type_str,code,target,value,replicas,want", CASES)
    def test_scalar_oracle(self, type_str, code, target, value, replicas, want):
        got = Proportional().get_desired_replicas(
            Metric(value=value, target_type=type_str, target_value=target), replicas
        )
        assert got == want

    @pytest.mark.parametrize("type_str,code,target,value,replicas,want", CASES)
    def test_device_kernel(self, type_str, code, target, value, replicas, want):
        out = D.decide_jit(single(value, target, code, replicas))
        assert int(out.recommendation[0]) == want


class TestSuiteGolden:
    """reference: horizontalautoscaler/v1alpha1/suite_test.go:94-118"""

    def test_utilization_85_over_60_with_5_replicas_wants_8(self):
        out = D.decide_jit(
            single(0.85, 60, D.TYPE_UTILIZATION, 5, min_replicas=3, max_replicas=23)
        )
        assert int(out.desired[0]) == 8
        assert bool(out.able_to_scale[0])
        assert bool(out.scaling_unbounded[0])

    def test_queue_41_target_4_average_value_wants_11(self):
        out = D.decide_jit(
            single(41, 4, D.TYPE_AVERAGE_VALUE, 1, min_replicas=0, max_replicas=1000)
        )
        assert int(out.desired[0]) == 11


class TestLimits:
    def test_max_clamp_marks_bounded(self):
        out = D.decide_jit(
            single(10, 1, D.TYPE_AVERAGE_VALUE, 1, min_replicas=0, max_replicas=5)
        )
        assert int(out.desired[0]) == 5
        assert not bool(out.scaling_unbounded[0])

    def test_min_clamp(self):
        out = D.decide_jit(
            single(0, 4, D.TYPE_AVERAGE_VALUE, 5, min_replicas=2, max_replicas=10)
        )
        assert int(out.desired[0]) == 2
        assert not bool(out.scaling_unbounded[0])

    def test_stabilization_window_blocks_scale_down(self):
        out = D.decide_jit(
            single(
                1,
                4,
                D.TYPE_AVERAGE_VALUE,
                5,
                min_replicas=0,
                max_replicas=10,
                last_scale_time=[100.0],
                has_last_scale=[True],
                now=200.0,  # 100s since last scale < 300s window
            )
        )
        assert int(out.desired[0]) == 5  # held at current
        assert not bool(out.able_to_scale[0])
        assert float(out.able_at[0]) == 400.0

    def test_scale_up_not_blocked_by_down_window(self):
        out = D.decide_jit(
            single(
                10,
                1,
                D.TYPE_AVERAGE_VALUE,
                5,
                min_replicas=0,
                max_replicas=100,
                last_scale_time=[100.0],
                has_last_scale=[True],
                now=101.0,
            )
        )
        assert int(out.desired[0]) == 10
        assert bool(out.able_to_scale[0])

    def test_expired_window_allows_scale_down(self):
        out = D.decide_jit(
            single(
                1,
                4,
                D.TYPE_AVERAGE_VALUE,
                5,
                min_replicas=0,
                max_replicas=10,
                last_scale_time=[100.0],
                has_last_scale=[True],
                now=401.0,
            )
        )
        assert int(out.desired[0]) == 1
        assert bool(out.able_to_scale[0])

    def test_no_metrics_disabled(self):
        inputs = make_inputs(
            metric_value=[[0.0]],
            target_value=[[0.0]],
            target_type=[[D.TYPE_VALUE]],
            metric_valid=[[False]],
            spec_replicas=[7],
            status_replicas=[7],
            min_replicas=[0],
            max_replicas=[100],
        )
        out = D.decide_jit(inputs)
        assert int(out.desired[0]) == 7

    def test_min_policy_select(self):
        inputs = make_inputs(
            metric_value=[[10.0, 20.0]],
            target_value=[[1.0, 1.0]],
            target_type=[[D.TYPE_AVERAGE_VALUE, D.TYPE_AVERAGE_VALUE]],
            metric_valid=[[True, True]],
            spec_replicas=[5],
            status_replicas=[5],
            min_replicas=[0],
            max_replicas=[100],
            up_policy=[D.POLICY_MIN],
        )
        out = D.decide_jit(inputs)
        assert int(out.desired[0]) == 10

    def test_zero_target_matches_scalar_oracle(self):
        # oracle: ratio collapses to 0 -> Value type floors at 1
        out = D.decide_jit(
            single(50, 0, D.TYPE_VALUE, 8, min_replicas=0, max_replicas=1000)
        )
        want = Proportional().get_desired_replicas(
            Metric(value=50, target_type=VALUE, target_value=0), 8
        )
        assert int(out.recommendation[0]) == want == 1

    def test_huge_recommendation_saturates_not_wraps(self):
        out = D.decide_jit(
            single(3e9, 1, D.TYPE_AVERAGE_VALUE, 1, min_replicas=0, max_replicas=2**31 - 1)
        )
        assert int(out.desired[0]) > 0  # must not wrap to INT32_MIN
        assert int(out.recommendation[0]) > 0

    def test_disabled_policy_keeps_replicas(self):
        inputs = make_inputs(
            metric_value=[[10.0]],
            target_value=[[1.0]],
            target_type=[[D.TYPE_AVERAGE_VALUE]],
            metric_valid=[[True]],
            spec_replicas=[5],
            status_replicas=[5],
            min_replicas=[0],
            max_replicas=[100],
            up_policy=[D.POLICY_DISABLED],
        )
        out = D.decide_jit(inputs)
        assert int(out.desired[0]) == 5


class TestScalingPolicies:
    """Count/Percent policies with periodSeconds — the reference MODELS
    these (horizontalautoscaler.go:111-146) but leaves application a TODO
    (autoscaler.go:186-189); the kernel applies them."""

    def up(self, policies, *, spec=5, want_value=100.0, last=None, now=500.0,
           select=None, max_replicas=1000):
        kw = dict(
            spec_replicas=[spec],
            status_replicas=[spec],
            min_replicas=[0],
            max_replicas=[max_replicas],
            up_policies=[policies],
            now=now,
        )
        if last is not None:
            kw["last_scale_time"] = [last]
            kw["has_last_scale"] = [True]
        if select is not None:
            kw["up_policy"] = [select]
        return D.decide_jit(
            make_inputs(
                metric_value=[[want_value]],
                target_value=[[1.0]],
                target_type=[[D.TYPE_AVERAGE_VALUE]],
                metric_valid=[[True]],
                **kw,
            )
        )

    def test_count_policy_caps_scale_up(self):
        # wants 100, budget 4 per 60s, last scale 120s ago -> 5+4=9
        out = self.up([(D.POLICY_TYPE_COUNT, 4, 60)], last=380.0)
        assert int(out.desired[0]) == 9
        assert bool(out.rate_limited[0])
        assert bool(out.able_to_scale[0])  # partial clamp still scales

    def test_percent_policy_caps_scale_up(self):
        # ceil(5 * 50%) = 3 -> 5+3=8
        out = self.up([(D.POLICY_TYPE_PERCENT, 50, 60)], last=380.0)
        assert int(out.desired[0]) == 8
        assert bool(out.rate_limited[0])

    def test_budget_spent_within_period_holds_entirely(self):
        # last scale 30s ago < 60s period: conservative 0 budget, full hold
        out = self.up([(D.POLICY_TYPE_COUNT, 4, 60)], last=470.0)
        assert int(out.desired[0]) == 5
        assert not bool(out.able_to_scale[0])
        assert bool(out.rate_limited[0])
        assert float(out.able_at[0]) == 470.0 + 60.0  # budget frees then

    def test_percent_policy_escapes_zero_replicas(self):
        # percent-of-zero would deadlock at 0 forever; the budget floors
        # current at 1 so at least ceil(value/100) movement is permitted
        out = self.up(
            [(D.POLICY_TYPE_PERCENT, 50, 60)], spec=0, last=380.0
        )
        assert int(out.desired[0]) == 1  # 0 + ceil(1*50%)=1
        assert bool(out.able_to_scale[0])

    def test_no_scale_history_is_unlimited(self):
        out = self.up([(D.POLICY_TYPE_COUNT, 4, 60)])  # has_last_scale=False
        assert int(out.desired[0]) == 100
        assert not bool(out.rate_limited[0])

    def test_max_select_takes_most_permissive(self):
        out = self.up(
            [(D.POLICY_TYPE_COUNT, 2, 60), (D.POLICY_TYPE_PERCENT, 100, 60)],
            last=380.0,
        )  # max(2, ceil(5*100%)=5) = 5 -> 10
        assert int(out.desired[0]) == 10

    def test_min_select_takes_most_restrictive(self):
        out = self.up(
            [(D.POLICY_TYPE_COUNT, 2, 60), (D.POLICY_TYPE_PERCENT, 100, 60)],
            last=380.0,
            select=D.POLICY_MIN,
        )  # min(2, 5) = 2 -> 7
        assert int(out.desired[0]) == 7

    def test_down_policy_caps_scale_down(self):
        out = D.decide_jit(
            make_inputs(
                metric_value=[[1.0]],
                target_value=[[1.0]],
                target_type=[[D.TYPE_AVERAGE_VALUE]],
                metric_valid=[[True]],
                spec_replicas=[50],
                status_replicas=[50],
                min_replicas=[0],
                max_replicas=[100],
                down_window=[0],
                down_policies=[[(D.POLICY_TYPE_PERCENT, 10, 60)]],
                last_scale_time=[100.0],
                has_last_scale=[True],
                now=500.0,
            )
        )
        # wants 1, allowed down ceil(50*10%)=5 -> 45
        assert int(out.desired[0]) == 45
        assert bool(out.rate_limited[0])

    def test_scalar_oracle_agrees(self):
        from karpenter_tpu.api.horizontalautoscaler import ScalingPolicy

        rules = ScalingRules(
            policies=[
                ScalingPolicy(type="Count", value=2, period_seconds=60),
                ScalingPolicy(type="Percent", value=100, period_seconds=60),
            ]
        )
        assert rules.allowed_change(5, last_scale_time=380.0, now=500.0) == 5
        rules.select_policy = "Min"
        assert rules.allowed_change(5, last_scale_time=380.0, now=500.0) == 2
        assert rules.allowed_change(5, last_scale_time=470.0, now=500.0) == 0
        assert rules.allowed_change(5, None, now=500.0) is None
        assert ScalingRules().allowed_change(5, 380.0, now=500.0) is None


def scalar_pipeline(
    values,
    targets,
    types,
    spec_replicas,
    status_replicas,
    min_replicas,
    max_replicas,
    behavior,
    last_scale_time,
    now,
):
    """Host mirror of autoscaler.go:144-194 used as the oracle."""
    algorithm = Proportional()
    recs = [
        algorithm.get_desired_replicas(
            Metric(value=v, target_type=t, target_value=tv), status_replicas
        )
        for v, tv, t in zip(values, targets, types)
    ]
    if recs:
        recommendation = behavior.apply_select_policy(spec_replicas, recs)
    else:
        recommendation = spec_replicas
    rules = behavior.get_scaling_rules(spec_replicas, [recommendation])
    if rules.within_stabilization_window(last_scale_time, now=now):
        limited = spec_replicas
    else:
        limited = recommendation
    allowed = rules.allowed_change(spec_replicas, last_scale_time, now=now)
    if allowed is not None:
        limited = min(
            max(limited, spec_replicas - allowed), spec_replicas + allowed
        )
    return int(min(max(limited, min_replicas), max_replicas))


class TestPropertyVsOracle:
    def test_random_fleet_matches_scalar_pipeline(self):
        rng = np.random.default_rng(42)
        n, m = 256, 3
        type_strs = np.array([VALUE, AVERAGE_VALUE, UTILIZATION, ""])
        type_codes = {
            VALUE: D.TYPE_VALUE,
            AVERAGE_VALUE: D.TYPE_AVERAGE_VALUE,
            UTILIZATION: D.TYPE_UTILIZATION,
            "": D.TYPE_UNKNOWN,
        }
        values = rng.choice([0.0, 0.25, 0.85, 1.0, 3.0, 41.0, 304.0, 1000.0], (n, m))
        targets = rng.choice([0.0, 1.0, 3.0, 4.0, 50.0, 60.0, 100.0], (n, m))
        types = rng.choice(type_strs, (n, m))
        valid = rng.random((n, m)) > 0.25
        spec = rng.integers(0, 50, n)
        status = rng.integers(0, 50, n)
        mins = rng.integers(0, 10, n)
        maxs = mins + rng.integers(0, 100, n)
        has_last = rng.random(n) > 0.5
        last = rng.uniform(0, 1000, n).astype(np.float32)
        now = np.float32(1000.0)
        down_window = rng.choice([0, 60, 300], n)
        up_window = rng.choice([0, 60], n)

        def random_policies():
            out = []
            for _ in range(n):
                if rng.random() < 0.5:
                    out.append([])
                else:
                    out.append(
                        [
                            (
                                int(rng.integers(0, 2)),
                                int(rng.integers(1, 11)),
                                int(rng.choice([30, 60, 300, 900])),
                            )
                            for _ in range(rng.integers(1, 3))
                        ]
                    )
            return out

        up_policies = random_policies()
        down_policies = random_policies()

        inputs = make_inputs(
            metric_value=values,
            target_value=targets,
            target_type=np.vectorize(type_codes.get)(types),
            metric_valid=valid,
            spec_replicas=spec,
            status_replicas=status,
            min_replicas=mins,
            max_replicas=maxs,
            up_window=up_window,
            down_window=down_window,
            last_scale_time=last,
            has_last_scale=has_last,
            now=now,
            up_policies=up_policies,
            down_policies=down_policies,
        )
        out = D.decide_jit(inputs)

        from karpenter_tpu.api.horizontalautoscaler import ScalingPolicy

        to_api = lambda triples: [
            ScalingPolicy(
                type="Percent" if t == D.POLICY_TYPE_PERCENT else "Count",
                value=v,
                period_seconds=p,
            )
            for t, v, p in triples
        ] or None

        for i in range(n):
            behavior = Behavior(
                scale_up=ScalingRules(
                    stabilization_window_seconds=int(up_window[i]),
                    policies=to_api(up_policies[i]),
                ),
                scale_down=ScalingRules(
                    stabilization_window_seconds=int(down_window[i]),
                    policies=to_api(down_policies[i]),
                ),
            )
            vals = [values[i][j] for j in range(m) if valid[i][j]]
            tgts = [targets[i][j] for j in range(m) if valid[i][j]]
            tps = [types[i][j] for j in range(m) if valid[i][j]]
            want = scalar_pipeline(
                vals,
                tgts,
                tps,
                int(spec[i]),
                int(status[i]),
                int(mins[i]),
                int(maxs[i]),
                behavior,
                float(last[i]) if has_last[i] else None,
                float(now),
            )
            assert int(out.desired[i]) == want, (
                f"row {i}: kernel={int(out.desired[i])} oracle={want} "
                f"vals={vals} tgts={tgts} tps={tps} spec={spec[i]} "
                f"status={status[i]} bounds=[{mins[i]},{maxs[i]}] "
                f"last={last[i] if has_last[i] else None}"
            )
