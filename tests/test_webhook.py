"""Admission webhook: AdmissionReview v1 validate/mutate over HTTP(S).

reference: the per-CRD Validator/Defaulter webhooks the manager registers
(pkg/controllers/manager.go:61-68) and the webhook admission rules exercised
by envtest (pkg/test/environment/local.go:74-77). Same rules, same wire
protocol, served by karpenter_tpu.webhook.WebhookServer.
"""

import base64
import json
import shutil
import ssl
import subprocess
import urllib.request

import pytest

from karpenter_tpu.webhook import (
    WebhookServer,
    json_patch,
    review_mutate,
    review_validate,
)


def review(manifest, uid="test-uid"):
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {"uid": uid, "operation": "CREATE", "object": manifest},
    }


def ha_manifest(min_replicas=1, max_replicas=10):
    return {
        "apiVersion": "autoscaling.karpenter.sh/v1alpha1",
        "kind": "HorizontalAutoscaler",
        "metadata": {"name": "ha", "namespace": "default"},
        "spec": {
            "scaleTargetRef": {
                "apiVersion": "autoscaling.karpenter.sh/v1alpha1",
                "kind": "ScalableNodeGroup",
                "name": "group",
            },
            "minReplicas": min_replicas,
            "maxReplicas": max_replicas,
        },
    }


def schedule_manifest(weekday="Monday"):
    return {
        "apiVersion": "autoscaling.karpenter.sh/v1alpha1",
        "kind": "MetricsProducer",
        "metadata": {"name": "mp", "namespace": "default"},
        "spec": {
            "scheduleSpec": {
                "timezone": "America/Los_Angeles",
                "defaultReplicas": 1,
                "behaviors": [
                    {
                        "replicas": 5,
                        "start": {"weekdays": [weekday], "hours": ["9"]},
                        "end": {"weekdays": [weekday], "hours": ["17"]},
                    }
                ],
            }
        },
    }


class TestReviewHandlers:
    def test_validate_allows_good_object(self):
        out = review_validate(review(ha_manifest()))
        assert out["response"] == {"uid": "test-uid", "allowed": True}
        assert out["kind"] == "AdmissionReview"

    def test_validate_denies_min_over_max(self):
        out = review_validate(review(ha_manifest(min_replicas=9, max_replicas=2)))
        assert out["response"]["allowed"] is False
        assert "maxReplicas" in out["response"]["status"]["message"]

    def test_validate_denies_bad_cron_field(self):
        out = review_validate(review(schedule_manifest(weekday="Blursday")))
        assert out["response"]["allowed"] is False

    def test_validate_denies_unknown_kind(self):
        out = review_validate(
            review({"kind": "Gadget", "apiVersion": "v1", "metadata": {}})
        )
        assert out["response"]["allowed"] is False

    def test_mutate_noop_defaults_produce_no_patch(self):
        # reference defaulting for these kinds is a no-op at admission time
        # (behavior defaults merge at decision time, GetScalingRules)
        out = review_mutate(review(ha_manifest()))
        assert out["response"]["allowed"] is True
        assert "patch" not in out["response"]

    def test_mutate_denies_undecodable_object(self):
        out = review_mutate(review({"kind": "HorizontalAutoscaler"}))
        assert out["response"]["allowed"] is False


def with_apiserver_metadata(manifest):
    """What .request.object actually looks like on a real cluster: the
    apiserver has already populated metadata the model doesn't track
    (generation, managedFields, uid, RFC3339 creationTimestamp). A strict
    decode denies every CREATE/UPDATE of the CRDs once the webhook is
    installed — the round-1 advisor's high-severity finding."""
    manifest["metadata"].update(
        {
            "uid": "0b1e5e2e-3f74-4a1c-9d8f-2b8a4c7d6e5f",
            "resourceVersion": "8675309",
            "generation": 1,
            "creationTimestamp": "2026-07-29T12:00:00Z",
            "managedFields": [
                {
                    "manager": "kubectl-client-side-apply",
                    "operation": "Update",
                    "apiVersion": "autoscaling.karpenter.sh/v1alpha1",
                    "time": "2026-07-29T12:00:00Z",
                    "fieldsType": "FieldsV1",
                    "fieldsV1": {"f:spec": {}},
                }
            ],
        }
    )
    return manifest


class TestApiserverPopulatedObjects:
    def test_validate_allows_server_populated_metadata(self):
        out = review_validate(review(with_apiserver_metadata(ha_manifest())))
        assert out["response"]["allowed"] is True, out["response"]

    def test_validate_still_enforces_rules_on_server_objects(self):
        out = review_validate(
            review(
                with_apiserver_metadata(
                    ha_manifest(min_replicas=9, max_replicas=2)
                )
            )
        )
        assert out["response"]["allowed"] is False

    def test_validate_still_denies_typoed_spec_key(self):
        """Leniency is scoped to server-populated metadata/status — a
        typo'd SPEC key must stay a hard deny, not silently-dropped
        misconfig that 'works'."""
        manifest = with_apiserver_metadata(ha_manifest())
        manifest["spec"]["minReplica"] = manifest["spec"].pop("minReplicas")
        out = review_validate(review(manifest))
        assert out["response"]["allowed"] is False
        assert "minReplica" in out["response"]["status"]["message"]

    def test_validate_allows_status_with_server_timestamps(self):
        """UPDATE admission objects carry status whose condition timestamps
        are RFC3339 strings; status is dropped before decode (status writes
        don't go through admission)."""
        manifest = with_apiserver_metadata(ha_manifest())
        manifest["status"] = {
            "currentReplicas": 3,
            "conditions": [
                {
                    "type": "Active",
                    "status": "True",
                    "lastTransitionTime": "2026-07-29T12:00:00Z",
                }
            ],
        }
        out = review_validate(review(manifest))
        assert out["response"]["allowed"] is True, out["response"]

    def test_mutate_allows_and_never_patches_server_metadata(self):
        out = review_mutate(review(with_apiserver_metadata(ha_manifest())))
        assert out["response"]["allowed"] is True, out["response"]
        if "patch" in out["response"]:
            ops = json.loads(base64.b64decode(out["response"]["patch"]))
            # server-populated fields are absent from both round-trips, so
            # the defaulting patch must never add/remove/replace them
            assert not any(op["path"].startswith("/metadata") for op in ops)


class TestJsonPatch:
    def test_add_replace_remove(self):
        before = {"a": 1, "b": {"c": 2, "gone": 3}}
        after = {"a": 9, "b": {"c": 2, "new": 4}}
        ops = json_patch(before, after)
        assert {"op": "replace", "path": "/a", "value": 9} in ops
        assert {"op": "remove", "path": "/b/gone"} in ops
        assert {"op": "add", "path": "/b/new", "value": 4} in ops
        assert len(ops) == 3

    def test_path_escaping(self):
        ops = json_patch({}, {"a/b": {"c~d": 1}})
        assert ops == [{"op": "add", "path": "/a~1b", "value": {"c~d": 1}}]

    def test_patch_is_base64_json_when_present(self):
        # force a patch through the wire shape by defaulting a dict diff
        out = review_mutate(review(ha_manifest()))
        if "patch" in out["response"]:  # defensive: decode must round-trip
            json.loads(base64.b64decode(out["response"]["patch"]))


def _post(url, body, context=None):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=5, context=context) as resp:
        return resp.status, json.loads(resp.read())


class TestServer:
    def test_http_validate_and_mutate(self):
        server = WebhookServer(port=0, host="127.0.0.1")
        port = server.start()
        try:
            status, out = _post(
                f"http://127.0.0.1:{port}/validate", review(ha_manifest())
            )
            assert status == 200 and out["response"]["allowed"] is True
            status, out = _post(
                f"http://127.0.0.1:{port}/validate",
                review(ha_manifest(min_replicas=5, max_replicas=1)),
            )
            assert status == 200 and out["response"]["allowed"] is False
            status, out = _post(
                f"http://127.0.0.1:{port}/mutate", review(ha_manifest())
            )
            assert status == 200 and out["response"]["allowed"] is True
        finally:
            server.stop()

    def test_http_malformed_body_400(self):
        server = WebhookServer(port=0, host="127.0.0.1")
        port = server.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/validate",
                data=b"{not json",
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5)
            assert err.value.code == 400
        finally:
            server.stop()

    def test_http_unknown_path_404(self):
        server = WebhookServer(port=0, host="127.0.0.1")
        port = server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(f"http://127.0.0.1:{port}/nope", review(ha_manifest()))
            assert err.value.code == 404
        finally:
            server.stop()

    @pytest.mark.skipif(
        shutil.which("openssl") is None, reason="openssl not available"
    )
    def test_tls_serving(self, tmp_path):
        """Real apiservers require TLS on the webhook (reference: 9443 +
        cert-manager certs); assert the server actually speaks it."""
        crt, key = str(tmp_path / "tls.crt"), str(tmp_path / "tls.key")
        subprocess.run(
            [
                "openssl", "req", "-x509", "-newkey", "rsa:2048",
                "-keyout", key, "-out", crt, "-days", "1", "-nodes",
                "-subj", "/CN=127.0.0.1",
            ],
            check=True,
            capture_output=True,
        )
        server = WebhookServer(
            port=0, host="127.0.0.1", cert_file=crt, key_file=key
        )
        port = server.start()
        try:
            context = ssl.create_default_context()
            context.check_hostname = False
            context.verify_mode = ssl.CERT_NONE
            status, out = _post(
                f"https://127.0.0.1:{port}/validate",
                review(ha_manifest()),
                context=context,
            )
            assert status == 200 and out["response"]["allowed"] is True
        finally:
            server.stop()
