"""Incremental (delta) snapshot encoding — bit-identical parity with the
full re-encode.

The delta layer (pendingcapacity/encoder.SnapshotDeltaCache) caches the
last encode per (group-set, resource-universe) key and splices pod
add/remove/rebind deltas instead of rebuilding _pod_arrays/_group_arrays
each tick. Its ONLY license to exist is exact equality: every property
here pins delta-encoded inputs bitwise against encoder._encode_full on
the same snapshot, across churn histories, universe growth, profile
churn, and the constrained-fleet bailout — and pins the SOLVED outputs
equal on both the device (xla) and numpy fallback paths.
"""

import dataclasses

import numpy as np
import pytest

from karpenter_tpu.api.core import (
    Container,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
    Toleration,
)
from karpenter_tpu.metrics.producers.pendingcapacity import encoder
from karpenter_tpu.metrics.producers.pendingcapacity.encoder import (
    SnapshotDeltaCache,
    _encode_full,
)
from karpenter_tpu.store import Store
from karpenter_tpu.store.columnar import PendingPodCache
from karpenter_tpu.utils.quantity import Quantity


def pod(name, cpu="100m", mem="128Mi", node=None, selector=None,
        tolerations=None, extra=None):
    requests = {"cpu": Quantity.parse(cpu), "memory": Quantity.parse(mem)}
    for r, v in (extra or {}).items():
        requests[r] = Quantity.parse(v)
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PodSpec(
            node_name=node,
            containers=[Container(requests=requests)],
            node_selector=dict(selector or {}),
            tolerations=list(tolerations or []),
        ),
        status=PodStatus(phase="Pending"),
    )


def make_profiles():
    """Stable profile tuples — reused across ticks like NodeMirror's
    memo, which is what arms the delta cache's identity check."""
    return [
        ({"cpu": 8.0, "memory": 32.0 * 1024**3, "pods": 110.0},
         {("zone", "z"), ("group", "a")}, set()),
        ({"cpu": 64.0, "memory": 256.0 * 1024**3, "pods": 110.0},
         {("group", "b")},
         {("dedicated", "infra", "NoSchedule")}),
    ]


def assert_inputs_identical(got, want):
    """Bitwise equality over every BinPackInputs field, including the
    None-ness of optional operands."""
    for field in dataclasses.fields(want):
        a = getattr(got, field.name)
        b = getattr(want, field.name)
        if b is None or a is None:
            assert a is None and b is None, field.name
            continue
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=field.name
        )


def assert_outputs_equal(got, want):
    for name in ("assigned", "assigned_count", "nodes_needed", "lp_bound"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)),
            np.asarray(getattr(want, name)),
            err_msg=name,
        )
    assert int(got.unschedulable) == int(want.unschedulable)


class TestDeltaParity:
    def test_bitwise_identical_under_randomized_churn(self):
        """Adds, removes, rebinds, shape mutations, slot reuse: after
        EVERY mutation the delta encode equals a fresh full encode, and
        the sequence actually exercises the delta/hit paths (no silent
        always-full fallback)."""
        rng = np.random.default_rng(3)
        store = Store()
        cache = PendingPodCache(store)
        delta = SnapshotDeltaCache()
        profiles = make_profiles()
        cpus = ["100m", "250m", "1", "2"]
        tol = [Toleration(key="dedicated", operator="Equal",
                          value="infra", effect="NoSchedule")]
        live = {}
        for step in range(80):
            op = rng.random()
            if op < 0.45 or not live:
                name = f"p{step}"
                store.create(pod(
                    name,
                    cpu=str(rng.choice(cpus)),
                    selector={"zone": "z"} if rng.random() < 0.3 else None,
                    tolerations=tol if rng.random() < 0.2 else None,
                ))
                live[name] = True
            elif op < 0.65:
                victim = str(rng.choice(list(live)))
                store.delete("Pod", "default", victim)
                del live[victim]
            elif op < 0.8:
                # rebind: the pod schedules away (leaves the pending set)
                victim = str(rng.choice(list(live)))
                store.update(pod(victim, node="n1"))
                del live[victim]
            else:
                victim = str(rng.choice(list(live)))
                store.update(pod(victim, cpu=str(rng.choice(cpus))))
            snap = cache.snapshot()
            assert_inputs_identical(
                delta.encode(snap, profiles),
                _encode_full(snap, profiles),
            )
        assert delta.deltas > 0, "churn never took the delta path"
        assert delta.fulls >= 1  # the cold build

    def test_unchanged_and_identical_shape_churn_hit_identity(self):
        """An unchanged dedup set — including a pod replaced by another
        with the IDENTICAL spec — returns the SAME inputs object, so
        identity-keyed device caches skip the re-upload."""
        store = Store()
        cache = PendingPodCache(store)
        delta = SnapshotDeltaCache()
        profiles = make_profiles()
        for i in range(5):
            store.create(pod(f"p{i}", cpu="2"))
        first = delta.encode(cache.snapshot(), profiles)
        # unchanged tick
        assert delta.encode(cache.snapshot(), profiles) is first
        # identical-shape churn: delete + recreate the same shape
        store.delete("Pod", "default", "p0")
        store.create(pod("replacement", cpu="2"))
        snap = cache.snapshot()
        assert snap.generation > 0
        again = delta.encode(snap, profiles)
        assert again is first
        assert_inputs_identical(again, _encode_full(snap, profiles))
        assert delta.hits >= 2

    def test_universe_growth_invalidates_and_stays_exact(self):
        """A new extended resource or selector label changes the cache
        key (universe invalidation); encodes remain bit-identical
        through the transition and after."""
        store = Store()
        cache = PendingPodCache(store)
        delta = SnapshotDeltaCache()
        profiles = make_profiles()
        store.create(pod("a", cpu="1"))
        delta.encode(cache.snapshot(), profiles)
        store.create(pod("gpu", extra={"vendor.io/tpu": "4"}))
        snap = cache.snapshot()
        assert_inputs_identical(
            delta.encode(snap, profiles), _encode_full(snap, profiles)
        )
        store.create(pod("picky", selector={"disk": "ssd"}))
        snap = cache.snapshot()
        assert_inputs_identical(
            delta.encode(snap, profiles), _encode_full(snap, profiles)
        )
        # post-transition churn rides the (new) delta entry again
        deltas_before = delta.deltas
        store.create(pod("b", cpu="1"))
        snap = cache.snapshot()
        assert_inputs_identical(
            delta.encode(snap, profiles), _encode_full(snap, profiles)
        )
        assert delta.deltas == deltas_before + 1

    def test_profile_churn_invalidates(self):
        """Fresh profile objects (node churn recomputes them) must miss
        the identity check and rebuild — never serve stale group
        arrays."""
        store = Store()
        cache = PendingPodCache(store)
        delta = SnapshotDeltaCache()
        store.create(pod("a", cpu="1"))
        snap = cache.snapshot()
        profiles = make_profiles()
        first = delta.encode(snap, profiles)
        grown = [
            ({"cpu": 16.0, "memory": 64.0 * 1024**3, "pods": 110.0},
             {("zone", "z"), ("group", "a")}, set()),
            profiles[1],
        ]
        second = delta.encode(snap, grown)
        assert second is not first
        assert_inputs_identical(second, _encode_full(snap, grown))

    def test_constrained_fleet_falls_back_to_full(self):
        """Live affinity/spread/anti rows route to the full encoder —
        the delta path never has to reproduce mask/score/expansion
        semantics."""
        from karpenter_tpu.api.core import (
            Affinity,
            NodeAffinity,
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
        )

        store = Store()
        cache = PendingPodCache(store)
        delta = SnapshotDeltaCache()
        profiles = make_profiles()
        store.create(pod("plain", cpu="1"))
        delta.encode(cache.snapshot(), profiles)
        affinity = Affinity(
            node_affinity=NodeAffinity(
                required_during_scheduling_ignored_during_execution=(
                    NodeSelector(
                        node_selector_terms=[
                            NodeSelectorTerm(
                                match_expressions=[
                                    NodeSelectorRequirement(
                                        key="zone",
                                        operator="In",
                                        values=["z"],
                                    )
                                ]
                            )
                        ]
                    )
                )
            )
        )
        constrained = pod("picky", cpu="1")
        constrained.spec.affinity = affinity
        store.create(constrained)
        fulls_before = delta.fulls
        snap = cache.snapshot()
        got = delta.encode(snap, profiles)
        assert delta.fulls == fulls_before + 1
        want = _encode_full(snap, profiles)
        assert want.pod_group_forbidden is not None  # constraint is live
        assert_inputs_identical(got, want)

    def test_drain_to_empty_and_refill(self):
        store = Store()
        cache = PendingPodCache(store)
        delta = SnapshotDeltaCache()
        profiles = make_profiles()
        for i in range(4):
            store.create(pod(f"p{i}"))
        delta.encode(cache.snapshot(), profiles)
        for i in range(4):
            store.delete("Pod", "default", f"p{i}")
        snap = cache.snapshot()
        assert_inputs_identical(
            delta.encode(snap, profiles), _encode_full(snap, profiles)
        )
        store.create(pod("fresh", cpu="4"))
        snap = cache.snapshot()
        assert_inputs_identical(
            delta.encode(snap, profiles), _encode_full(snap, profiles)
        )

    def test_with_rows_and_census_bypass_the_cache(self):
        store = Store()
        cache = PendingPodCache(store)
        delta = SnapshotDeltaCache()
        profiles = make_profiles()
        store.create(pod("a"))
        snap = cache.snapshot()
        inputs, row_idx, row_weight = delta.encode(
            snap, profiles, with_rows=True
        )
        want, want_idx, want_w = _encode_full(
            snap, profiles, with_rows=True
        )
        assert_inputs_identical(inputs, want)
        np.testing.assert_array_equal(row_idx, want_idx)
        np.testing.assert_array_equal(row_weight, want_w)


class TestSolvedParity:
    """Delta-encoded inputs must SOLVE identically to full-encoded ones
    on both the device (xla) and numpy fallback paths — the encode is
    upstream of every backend, so parity must survive the dispatch."""

    @pytest.mark.parametrize("backend", ["xla", "numpy"])
    def test_solved_outputs_equal(self, backend):
        from karpenter_tpu.ops import binpack as B
        from karpenter_tpu.ops.numpy_binpack import binpack_numpy

        store = Store()
        cache = PendingPodCache(store)
        delta = SnapshotDeltaCache()
        profiles = make_profiles()
        rng = np.random.default_rng(5)
        for i in range(20):
            store.create(pod(f"p{i}", cpu=str(rng.choice(["1", "2"]))))
        delta.encode(cache.snapshot(), profiles)  # cold entry
        store.delete("Pod", "default", "p3")
        store.create(pod("late", cpu="4"))
        snap = cache.snapshot()
        got = delta.encode(snap, profiles)
        want = _encode_full(snap, profiles)
        assert delta.deltas >= 1
        solve = (
            (lambda x: binpack_numpy(x, buckets=16))
            if backend == "numpy"
            else (lambda x: B.solve(x, buckets=16, backend="xla"))
        )
        assert_outputs_equal(solve(got), solve(want))


class TestDefaultSeam:
    def test_encode_snapshot_routes_through_default_delta(self):
        """The public encode_snapshot rides the process-default delta
        cache: two encodes of an unchanged snapshot return the same
        object."""
        from karpenter_tpu.metrics.producers import pendingcapacity as PC

        store = Store()
        cache = PendingPodCache(store)
        store.create(pod("a", cpu="7"))  # distinctive shape
        profiles = make_profiles()
        snap = cache.snapshot()
        first = PC.encode_snapshot(snap, profiles)
        assert PC.encode_snapshot(snap, profiles) is first
        assert encoder._default_delta.hits >= 1
