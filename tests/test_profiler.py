"""observability/profiler.py: the probe-once device-timeline hooks and
the on-demand /debug/profile capture.

Pins (ISSUE 15 satellites):

  * jax absent/broken -> `solver_trace` returns the SHARED no-op
    annotation, and the probe result is CACHED (one import attempt per
    process, not one per dispatch);
  * with jax.profiler present the TraceAnnotation class is actually
    used, and a broken annotation SETUP is swallowed while exceptions
    from the traced block itself propagate;
  * `start_profiler_server` logs its failure reason instead of
    returning False silently;
  * `/debug/profile?ms=N` captures bounded + single-flight into the
    journal dir (atomic rename, manifest stamped with the active trace
    id) and answers 503 when the probe failed or nothing is wired.
"""

import json
import os
import sys
import urllib.error
import urllib.request

import pytest

from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.observability import MetricsServer
from karpenter_tpu.observability import profiler as P


@pytest.fixture
def fresh_probe():
    """Reset the probe cache around each test (module-global state)."""
    P.reset_probe()
    yield
    P.reset_probe()


class TestProbeOnce:
    def test_broken_jax_profiler_yields_shared_noop(
        self, fresh_probe, monkeypatch
    ):
        # sys.modules[name] = None makes `import jax.profiler` raise
        # ImportError — the "jax absent/broken" environment
        monkeypatch.setitem(sys.modules, "jax.profiler", None)
        span = P.solver_trace("solver.dispatch")
        assert span is P._NOOP_TRACE
        # the probe is CACHED as unavailable: restoring the module does
        # not resurrect annotations until reset_probe
        monkeypatch.undo()
        assert P._ANNOTATION_CLS is False
        assert P.solver_trace("again") is P._NOOP_TRACE
        # the no-op is a working context manager
        with P.solver_trace("x"):
            pass

    def test_probe_caches_available_class(self, fresh_probe):
        first = P.solver_trace("a")
        assert isinstance(first, P._GuardedAnnotation)
        cached = P._ANNOTATION_CLS
        assert cached is not None and cached is not False
        P.solver_trace("b")
        assert P._ANNOTATION_CLS is cached  # no re-probe

    def test_annotation_class_used_when_present(self, fresh_probe):
        entered = []

        class FakeAnnotation:
            def __init__(self, name):
                entered.append(name)

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False

        P._ANNOTATION_CLS = FakeAnnotation
        with P.solver_trace("solver.cost"):
            pass
        assert entered == ["solver.cost"]

    def test_guarded_annotation_swallows_setup_failures(self):
        class BrokenAnnotation:
            def __init__(self, name):
                raise RuntimeError("profiler backend fell over")

        # setup failure is swallowed; the block still runs
        ran = []
        with P._GuardedAnnotation(BrokenAnnotation, "x"):
            ran.append(True)
        assert ran == [True]
        # ...but an exception FROM the block propagates unchanged
        class FineAnnotation:
            def __init__(self, name):
                pass

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False

        with pytest.raises(ValueError):
            with P._GuardedAnnotation(FineAnnotation, "x"):
                raise ValueError("the solve's own error")


class TestProfilerServer:
    def test_failure_reason_is_logged(
        self, fresh_probe, monkeypatch, caplog
    ):
        import logging

        monkeypatch.setitem(sys.modules, "jax.profiler", None)
        with caplog.at_level(logging.WARNING, logger="karpenter"):
            assert P.start_profiler_server(port=59999) is False
        assert "failed to start" in caplog.text


class TestCaptureProfile:
    def test_capture_writes_atomic_dir_with_manifest(
        self, fresh_probe, tmp_path
    ):
        report = P.capture_profile(
            ms=10, out_dir=str(tmp_path), trace_id="t00000a1"
        )
        assert os.path.isdir(report["path"])
        assert not report["path"].endswith(".tmp")
        assert os.path.basename(report["path"]).startswith(
            P.PROFILE_PREFIX
        )
        manifest = json.load(
            open(os.path.join(report["path"], "manifest.json"))
        )
        assert manifest["trace_id"] == "t00000a1"
        assert manifest["ms_requested"] == 10
        assert manifest["ms_captured"] >= 10
        # no orphan tmp dirs on the happy path
        assert not [
            name for name in os.listdir(tmp_path)
            if name.endswith(".tmp")
        ]

    def test_bounds_clamp(self, fresh_probe, tmp_path):
        report = P.capture_profile(ms=-50, out_dir=str(tmp_path))
        assert report["ms_requested"] == P.MIN_CAPTURE_MS

    def test_single_flight(self, fresh_probe, tmp_path):
        assert P._capture_lock.acquire(blocking=False)
        try:
            with pytest.raises(P.ProfileBusy):
                P.capture_profile(ms=10, out_dir=str(tmp_path))
        finally:
            P._capture_lock.release()

    def test_unavailable_probe_raises(
        self, fresh_probe, monkeypatch, tmp_path
    ):
        monkeypatch.setitem(sys.modules, "jax.profiler", None)
        with pytest.raises(P.ProfileUnavailable):
            P.capture_profile(ms=10, out_dir=str(tmp_path))


class TestDebugProfileEndpoint:
    def _get(self, url):
        # generous client timeout: stop_trace serializes the whole
        # process profile, which in a full-suite process that compiled
        # hundreds of XLA programs can take well over 10s
        try:
            with urllib.request.urlopen(url, timeout=120) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as error:
            return error.code, error.read()

    def test_capture_via_endpoint(self, fresh_probe, tmp_path):
        server = MetricsServer(
            GaugeRegistry(), port=0, host="127.0.0.1",
            profile_dir=str(tmp_path),
        )
        port = server.start()
        try:
            status, body = self._get(
                f"http://127.0.0.1:{port}/debug/profile?ms=10"
            )
            assert status == 200, body
            report = json.loads(body)
            assert os.path.isdir(report["path"])
            assert report["ms_requested"] == 10
        finally:
            server.stop()

    def test_no_journal_dir_is_503(self, fresh_probe):
        server = MetricsServer(GaugeRegistry(), port=0, host="127.0.0.1")
        port = server.start()
        try:
            status, body = self._get(
                f"http://127.0.0.1:{port}/debug/profile?ms=10"
            )
            assert status == 503
            assert b"journal-dir" in body
        finally:
            server.stop()

    def test_failed_probe_is_503(
        self, fresh_probe, monkeypatch, tmp_path
    ):
        monkeypatch.setitem(sys.modules, "jax.profiler", None)
        server = MetricsServer(
            GaugeRegistry(), port=0, host="127.0.0.1",
            profile_dir=str(tmp_path),
        )
        port = server.start()
        try:
            status, body = self._get(
                f"http://127.0.0.1:{port}/debug/profile?ms=10"
            )
            assert status == 503
            assert b"unavailable" in body
        finally:
            server.stop()

    def test_malformed_ms_is_400(self, fresh_probe, tmp_path):
        server = MetricsServer(
            GaugeRegistry(), port=0, host="127.0.0.1",
            profile_dir=str(tmp_path),
        )
        port = server.start()
        try:
            status, _body = self._get(
                f"http://127.0.0.1:{port}/debug/profile?ms=soon"
            )
            assert status == 400
        finally:
            server.stop()
