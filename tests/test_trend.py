"""Trend algorithm: predictive scaling through the reference's algorithm
seam (algorithm.go:37-39 leaves selection a TODO with Proportional
hardcoded; `autoscaling.karpenter.sh/algorithm: trend` selects this one).
The reference has no predictive capability — a ramping signal is always
chased from behind by poll-interval lag."""

from karpenter_tpu.api.horizontalautoscaler import AVERAGE_VALUE, UTILIZATION
from karpenter_tpu.autoscaler.algorithms import Metric
from karpenter_tpu.autoscaler.algorithms.proportional import Proportional
from karpenter_tpu.autoscaler.algorithms.trend import Trend


def metric(value, at, owner=("default", "ha"), name="q",
           target_type=AVERAGE_VALUE, target=10.0):
    return Metric(
        value=value,
        target_type=target_type,
        target_value=target,
        name=name,
        owner=owner,
        at=at,
    )


class TestTrendUnit:
    def test_rising_series_scales_ahead(self):
        trend = Trend(window=300.0, horizon=60.0)
        trend.get_desired_replicas(metric(10.0, at=0.0), 1)
        trend.get_desired_replicas(metric(20.0, at=30.0), 1)
        got = trend.get_desired_replicas(metric(30.0, at=60.0), 1)
        # slope 1/3 per second; projection = 30 + 60/3 = 50 -> ceil(5)
        assert got == 5
        assert Proportional().get_desired_replicas(
            metric(30.0, at=60.0), 1
        ) == 3

    def test_falling_series_is_plain_proportional(self):
        """Never scale down ahead of the data: down-scaling stays
        governed by stabilization windows, not projections."""
        trend = Trend()
        trend.get_desired_replicas(metric(30.0, at=0.0), 1)
        trend.get_desired_replicas(metric(20.0, at=30.0), 1)
        got = trend.get_desired_replicas(metric(10.0, at=60.0), 1)
        assert got == Proportional().get_desired_replicas(
            metric(10.0, at=60.0), 1
        )

    def test_single_sample_is_plain_proportional(self):
        trend = Trend()
        got = trend.get_desired_replicas(metric(25.0, at=0.0), 4)
        assert got == Proportional().get_desired_replicas(
            metric(25.0, at=0.0), 4
        )

    def test_narrow_window_never_extrapolates(self):
        """Two samples within a second (reconcile retry burst) carry no
        usable slope."""
        trend = Trend()
        trend.get_desired_replicas(metric(10.0, at=0.0), 1)
        got = trend.get_desired_replicas(metric(30.0, at=0.5), 1)
        assert got == 3  # plain ceil(30/10), no projection

    def test_backwards_clock_clears_the_window(self):
        trend = Trend()
        trend.get_desired_replicas(metric(10.0, at=100.0), 1)
        got = trend.get_desired_replicas(metric(30.0, at=50.0), 1)
        assert got == 3  # window restarted: single sample, plain math
        assert len(trend._series[trend._key(metric(0, 0))]) == 1

    def test_window_prunes_by_age(self):
        trend = Trend(window=60.0, horizon=60.0)
        trend.get_desired_replicas(metric(1000.0, at=0.0), 1)
        trend.get_desired_replicas(metric(10.0, at=100.0), 1)
        series = trend._series[trend._key(metric(0, 0))]
        assert [v for _, v in series] == [10.0]

    def test_label_sets_do_not_share_history(self):
        """Two specs over the same metric NAME with different label
        matchers must keep separate windows — interleaving them would
        fit a garbage sawtooth slope (r3 code review)."""
        trend = Trend()
        a = dict(owner=("default", "ha"), name="util")
        trend.get_desired_replicas(
            Metric(value=10.0, target_type=AVERAGE_VALUE,
                   target_value=10.0, labels={"name": "a"},
                   at=0.0, **a), 1)
        trend.get_desired_replicas(
            Metric(value=90.0, target_type=AVERAGE_VALUE,
                   target_value=10.0, labels={"name": "b"},
                   at=30.0, **a), 1)
        got = trend.get_desired_replicas(
            Metric(value=10.0, target_type=AVERAGE_VALUE,
                   target_value=10.0, labels={"name": "a"},
                   at=60.0, **a), 1)
        assert got == 1  # a's series is flat; no slope bleed from b

    def test_owners_do_not_share_history(self):
        trend = Trend()
        trend.get_desired_replicas(
            metric(10.0, at=0.0, owner=("default", "a")), 1
        )
        trend.get_desired_replicas(
            metric(99.0, at=30.0, owner=("default", "b")), 1
        )
        got = trend.get_desired_replicas(
            metric(10.0, at=60.0, owner=("default", "a")), 1
        )
        # owner a's series is flat: plain proportional, no slope from b
        assert got == 1

    def test_utilization_projection(self):
        trend = Trend(horizon=60.0)
        kwargs = dict(target_type=UTILIZATION, target=60.0)
        trend.get_desired_replicas(metric(0.60, at=0.0, **kwargs), 5)
        got = trend.get_desired_replicas(
            metric(0.708, at=60.0, **kwargs), 5
        )
        # slope 0.0018/s -> projection 0.816 -> ceil(5 * 81.6/60) = 7
        assert got == 7

    def test_stale_keys_prune_lazily(self):
        import karpenter_tpu.autoscaler.algorithms.trend as T

        trend = Trend(window=10.0)
        threshold = T._PRUNE_THRESHOLD
        for i in range(threshold + 1):
            trend.get_desired_replicas(
                metric(1.0, at=0.0, owner=("ns", f"ha{i}")), 1
            )
        assert len(trend._series) == threshold + 1
        # a much-later observation prunes every aged-out window
        trend.get_desired_replicas(
            metric(1.0, at=1000.0, owner=("ns", "fresh")), 1
        )
        assert len(trend._series) == 1  # only the fresh window survives


class TestTrendEndToEnd:
    def test_trend_annotation_scales_ahead_of_plain(self):
        """Two autoscalers watch the same ramping gauge; the trend one
        scales ahead, the default one reacts — through the full batch
        (host recommendation -> device select/stabilize/bound)."""
        from test_e2e import sng_of, utilization_ha

        from karpenter_tpu.autoscaler import algorithms
        from karpenter_tpu.cloudprovider.fake import FakeFactory
        from karpenter_tpu.runtime import KarpenterRuntime

        class Clock:
            def __init__(self):
                self.now = 1000.0

            def __call__(self):
                return self.now

        clock = Clock()
        provider = FakeFactory()
        runtime = KarpenterRuntime(
            cloud_provider_factory=provider, clock=clock
        )
        for name, annotate in (("ride-trend", True), ("plain", False)):
            gauge = runtime.registry.register(
                "reserved_capacity", "cpu_utilization"
            )
            gauge.set(name, "default", 0.60)
            provider.node_replicas[name] = 5
            runtime.store.create(sng_of(name, replicas=5))
            ha_obj = utilization_ha(
                name,
                queries=("karpenter_reserved_capacity_cpu_utilization",),
            )
            if annotate:
                ha_obj.metadata.annotations[
                    algorithms.ALGORITHM_ANNOTATION
                ] = "trend"
            runtime.store.create(ha_obj)

        runtime.manager.reconcile_all()  # 0.60 / target 60%: steady, 5
        clock.now += 60.0
        for name in ("ride-trend", "plain"):
            runtime.registry.gauge(
                "reserved_capacity", "cpu_utilization"
            ).set(name, "default", 0.708)
        runtime.manager.reconcile_all()

        trended = runtime.store.get(
            "HorizontalAutoscaler", "default", "ride-trend"
        )
        plain = runtime.store.get(
            "HorizontalAutoscaler", "default", "plain"
        )
        # ramp 0.60 -> 0.708 over 60 s: plain reacts to 70.8% (6 of 5);
        # trend projects 81.6% one horizon ahead (7)
        assert plain.status.desired_replicas == 6
        assert trended.status.desired_replicas == 7

    def test_trend_is_admitted(self):
        from test_e2e import utilization_ha

        from karpenter_tpu.autoscaler import algorithms

        ha_obj = utilization_ha("ok")
        ha_obj.metadata.annotations[
            algorithms.ALGORITHM_ANNOTATION
        ] = "trend"
        ha_obj.validate()  # must not raise: trend is registered
