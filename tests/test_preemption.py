"""Priority- and preemption-aware packing (ops/preempt.py,
karpenter_tpu/preemption, the solver service's `preempt` seam, and the
binpack priority/tier operands).

The acceptance pins:

  * XLA and numpy eviction plans are BIT-IDENTICAL (integer-capacity
    arithmetic — ops/preempt.py docstring), including through the
    service's shape-bucket padding;
  * batched plans equal independent per-candidate plans row for row
    (the candidate axis is data-parallel; quantization scales are
    fleet-derived, not candidate-derived);
  * priority-off inputs reproduce today's binpack outputs exactly —
    absent operands take the pre-existing code path, and explicit
    all-zero priority/tier operands produce identical outputs;
  * the engine's safety layer: budgets never exceeded, no duplicate
    evictions, do-not-disrupt respected, and the two disruption
    engines (preemption/consolidation) never touch one node at once.
"""

import numpy as np

from karpenter_tpu.api.core import (
    Container,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    capacity_tier_of,
    effective_priority,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    MetricsProducerSpec,
    PendingCapacitySpec,
)
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.ops import binpack as B
from karpenter_tpu.ops.numpy_binpack import binpack_numpy
from karpenter_tpu.ops.preempt import (
    PreemptInputs,
    preempt_numpy,
    preempt_plan,
    solve_preempt,
)
from karpenter_tpu.preemption import PreemptionConfig, PreemptionEngine
from karpenter_tpu.solver import SolverService
from karpenter_tpu.solver.bucketing import (
    crop_preempt_outputs,
    pad_preempt_inputs,
    preempt_bucket_shape,
)
from karpenter_tpu.store import Store
from karpenter_tpu.utils.quantity import Quantity

OUTPUT_FIELDS = ("chosen_node", "evict_count", "evict_mask", "unplaceable")


def random_problem(rng, c=None, n=None, v=None, r=None):
    """A seeded random eviction problem honoring the kernel's input
    contract (victims sorted by (node, priority, index))."""
    c = c if c is not None else int(rng.integers(1, 12))
    n = n if n is not None else int(rng.integers(1, 10))
    v = v if v is not None else int(rng.integers(0, 40))
    r = r if r is not None else int(rng.integers(1, 5))
    victim_node = np.sort(rng.integers(0, n, v)).astype(np.int32)
    victim_priority = np.zeros(v, np.int32)
    for col in range(n):
        seg = victim_node == col
        victim_priority[seg] = np.sort(
            rng.integers(0, 300, int(seg.sum()))
        )
    return PreemptInputs(
        pod_requests=rng.uniform(0.1, 5.0, (c, r)).astype(np.float32),
        pod_priority=rng.integers(0, 400, c).astype(np.int32),
        pod_valid=rng.random(c) < 0.9,
        pod_node_forbidden=rng.random((c, n)) < 0.15,
        node_free=rng.uniform(0.0, 3.0, (n, r)).astype(np.float32),
        node_tier=(rng.random(n) < 0.3).astype(np.int32),
        victim_requests=rng.uniform(0.05, 2.0, (v, r)).astype(
            np.float32
        ),
        victim_priority=victim_priority,
        victim_node=victim_node,
        victim_valid=rng.random(v) < 0.95,
        victim_evictable=rng.random(v) < 0.9,
    )


def assert_outputs_equal(a, b, context=""):
    for field in OUTPUT_FIELDS:
        left = np.asarray(getattr(a, field))
        right = np.asarray(getattr(b, field))
        assert np.array_equal(left, right), (
            f"{field} mismatch {context}: {left} vs {right}"
        )


def single_candidate(inputs, c):
    import dataclasses

    return dataclasses.replace(
        inputs,
        pod_requests=inputs.pod_requests[c : c + 1],
        pod_priority=inputs.pod_priority[c : c + 1],
        pod_valid=inputs.pod_valid[c : c + 1],
        pod_node_forbidden=inputs.pod_node_forbidden[c : c + 1],
    )


class TestKernelParity:
    def test_xla_equals_numpy_bit_identically(self):
        import jax

        rng = np.random.default_rng(7)
        for trial in range(25):
            inputs = random_problem(rng)
            host = preempt_numpy(inputs)
            device = preempt_plan(jax.device_put(inputs))
            assert_outputs_equal(host, device, f"(trial {trial})")

    def test_parity_survives_bucket_padding(self):
        import jax

        rng = np.random.default_rng(11)
        for trial in range(10):
            inputs = random_problem(rng)
            c = inputs.pod_requests.shape[0]
            v = inputs.victim_requests.shape[0]
            padded = pad_preempt_inputs(
                inputs, preempt_bucket_shape(inputs)
            )
            cropped = crop_preempt_outputs(
                preempt_numpy(padded), c, v
            )
            assert_outputs_equal(
                preempt_numpy(inputs), cropped, f"(numpy, trial {trial})"
            )
            cropped_dev = crop_preempt_outputs(
                preempt_plan(jax.device_put(padded)), c, v
            )
            # full-axis comparison after crop: padded device == raw host
            raw = preempt_numpy(inputs)
            for field in ("chosen_node", "evict_count", "evict_mask"):
                assert np.array_equal(
                    np.asarray(getattr(raw, field)),
                    np.asarray(getattr(cropped_dev, field)),
                ), f"{field} (device pad, trial {trial})"

    def test_quantization_scale_is_candidate_independent(self):
        """Regression (r6 review): the scale denominator must derive
        from the fleet (nodes + victims) only — a candidate-derived
        max would shift ceil/floor rounding with batch composition and
        flip borderline plans between batched and single-candidate
        submissions."""
        inputs = PreemptInputs(
            pod_requests=np.array(
                [[1.17], [2.87], [0.83], [2.60], [4.34]], np.float32
            ),
            pod_priority=np.full(5, 100, np.int32),
            pod_valid=np.ones(5, bool),
            pod_node_forbidden=np.zeros((5, 1), bool),
            node_free=np.array([[2.6019135]], np.float32),
            node_tier=np.zeros(1, np.int32),
            victim_requests=np.zeros((0, 1), np.float32),
            victim_priority=np.zeros(0, np.int32),
            victim_node=np.zeros(0, np.int32),
            victim_valid=np.zeros(0, bool),
            victim_evictable=np.zeros(0, bool),
        )
        batched = preempt_numpy(inputs)
        for c in range(5):
            one = preempt_numpy(single_candidate(inputs, c))
            assert int(one.chosen_node[0]) == int(
                batched.chosen_node[c]
            ), f"candidate {c}"

    def test_nodeless_fleet_is_unplaceable_on_both_backends(self):
        """Regression (r6 review): a fleet with zero node columns —
        e.g. a FULL spot reclaim — reports every valid candidate
        unplaceable on the raw numpy mirror too (the device path only
        ever saw N=0 through bucket padding)."""
        import jax

        inputs = PreemptInputs(
            pod_requests=np.array([[1.0], [2.0]], np.float32),
            pod_priority=np.array([100, 50], np.int32),
            pod_valid=np.array([True, False]),
            pod_node_forbidden=np.zeros((2, 0), bool),
            node_free=np.zeros((0, 1), np.float32),
            node_tier=np.zeros(0, np.int32),
            victim_requests=np.zeros((0, 1), np.float32),
            victim_priority=np.zeros(0, np.int32),
            victim_node=np.zeros(0, np.int32),
            victim_valid=np.zeros(0, bool),
            victim_evictable=np.zeros(0, bool),
        )
        host = preempt_numpy(inputs)
        assert np.asarray(host.chosen_node).tolist() == [-1, -1]
        assert int(host.unplaceable) == 1  # only the valid candidate
        assert_outputs_equal(
            host, preempt_plan(jax.device_put(inputs)), "(N=0)"
        )

    def test_empty_victim_axis(self):
        rng = np.random.default_rng(3)
        inputs = random_problem(rng, v=0)
        out = solve_preempt(inputs, backend="numpy")
        # with no victims every plan is a zero-eviction fit or nothing
        assert (np.asarray(out.evict_count) == 0).all()

    def test_plans_actually_fit(self):
        """Conservative quantization: an accepted plan's freed + free
        capacity covers the candidate — never an under-eviction."""
        rng = np.random.default_rng(5)
        for _ in range(10):
            inputs = random_problem(rng)
            out = preempt_numpy(inputs)
            chosen = np.asarray(out.chosen_node)
            mask = np.asarray(out.evict_mask)
            for c in range(chosen.shape[0]):
                col = int(chosen[c])
                if col < 0:
                    continue
                freed = inputs.victim_requests[mask[c]].sum(axis=0)
                assert (
                    inputs.node_free[col]
                    + freed
                    + 1e-3  # f32 verification slack only
                    >= inputs.pod_requests[c]
                ).all()


class TestBatchedIndependence:
    def test_batched_equals_per_candidate(self):
        rng = np.random.default_rng(13)
        for trial in range(10):
            inputs = random_problem(rng)
            batched = preempt_numpy(inputs)
            for c in range(inputs.pod_requests.shape[0]):
                one = preempt_numpy(single_candidate(inputs, c))
                assert int(one.chosen_node[0]) == int(
                    batched.chosen_node[c]
                ), f"candidate {c} (trial {trial})"
                assert int(one.evict_count[0]) == int(
                    batched.evict_count[c]
                )
                assert np.array_equal(
                    np.asarray(one.evict_mask)[0],
                    np.asarray(batched.evict_mask)[c],
                )

    def test_batched_equals_per_candidate_on_device(self):
        import jax

        rng = np.random.default_rng(17)
        inputs = random_problem(rng, c=6, n=5, v=24, r=3)
        batched = preempt_plan(jax.device_put(inputs))
        for c in range(6):
            one = preempt_plan(
                jax.device_put(single_candidate(inputs, c))
            )
            assert int(one.chosen_node[0]) == int(batched.chosen_node[c])
            assert int(one.evict_count[0]) == int(batched.evict_count[c])


class TestKernelSemantics:
    def fleet(self):
        """One 4-cpu node, three 1-cpu victims at priorities 10/20/30."""
        return PreemptInputs(
            pod_requests=np.array([[2.0]], np.float32),
            pod_priority=np.array([100], np.int32),
            pod_valid=np.ones(1, bool),
            pod_node_forbidden=np.zeros((1, 1), bool),
            node_free=np.array([[0.0]], np.float32),
            node_tier=np.zeros(1, np.int32),
            victim_requests=np.array(
                [[1.0], [1.0], [1.0]], np.float32
            ),
            victim_priority=np.array([10, 20, 30], np.int32),
            victim_node=np.zeros(3, np.int32),
            victim_valid=np.ones(3, bool),
            victim_evictable=np.ones(3, bool),
        )

    def test_minimal_prefix_evicts_lowest_priority_first(self):
        out = preempt_numpy(self.fleet())
        assert int(out.chosen_node[0]) == 0
        assert int(out.evict_count[0]) == 2
        assert np.asarray(out.evict_mask)[0].tolist() == [
            True, True, False,
        ]

    def test_higher_priority_victims_are_protected(self):
        import dataclasses

        inputs = dataclasses.replace(
            self.fleet(), pod_priority=np.array([15], np.int32)
        )
        out = preempt_numpy(inputs)
        # only the priority-10 victim is outranked: 1 cpu freed < 2
        assert int(out.chosen_node[0]) == -1
        assert int(out.unplaceable) == 1

    def test_spot_tier_is_evictable_by_contract(self):
        import dataclasses

        inputs = dataclasses.replace(
            self.fleet(),
            pod_priority=np.array([15], np.int32),
            node_tier=np.ones(1, np.int32),
        )
        out = preempt_numpy(inputs)
        assert int(out.chosen_node[0]) == 0
        assert int(out.evict_count[0]) == 2

    def test_do_not_disrupt_mask_respected(self):
        import dataclasses

        inputs = dataclasses.replace(
            self.fleet(),
            victim_evictable=np.array([False, True, True]),
        )
        out = preempt_numpy(inputs)
        # the protected lowest-priority victim is skipped, not evicted
        assert np.asarray(out.evict_mask)[0].tolist() == [
            False, True, True,
        ]

    def test_zero_eviction_fit_wins(self):
        import dataclasses

        base = self.fleet()
        inputs = dataclasses.replace(
            base,
            node_free=np.array([[0.0], [2.0]], np.float32),
            node_tier=np.zeros(2, np.int32),
            pod_node_forbidden=np.zeros((1, 2), bool),
        )
        out = preempt_numpy(inputs)
        assert int(out.chosen_node[0]) == 1
        assert int(out.evict_count[0]) == 0


class TestPriorityOffBinpack:
    """Acceptance pin (c): priority-off inputs reproduce today's
    binpack outputs exactly — and explicit zero operands change
    nothing either."""

    def problem(self, rng):
        p, t = 40, 6
        return dict(
            pod_requests=rng.uniform(0.1, 3.0, (p, 2)).astype(
                np.float32
            ),
            pod_valid=rng.random(p) < 0.95,
            pod_intolerant=rng.random((p, 4)) < 0.1,
            pod_required=rng.random((p, 4)) < 0.1,
            group_allocatable=rng.uniform(1.0, 4.0, (t, 2)).astype(
                np.float32
            ),
            group_taints=rng.random((t, 4)) < 0.2,
            group_labels=rng.random((t, 4)) < 0.5,
        )

    def test_absent_equals_zero_operands(self):
        import jax

        rng = np.random.default_rng(23)
        for trial in range(5):
            fields = self.problem(rng)
            absent = B.BinPackInputs(**fields)
            zeroed = B.BinPackInputs(
                **fields,
                pod_priority=np.zeros(
                    fields["pod_requests"].shape[0], np.int32
                ),
                group_tier=np.zeros(
                    fields["group_allocatable"].shape[0], np.int32
                ),
            )
            for solver in (
                lambda x: B.binpack(jax.device_put(x)),
                binpack_numpy,
            ):
                a, z = solver(absent), solver(zeroed)
                assert np.array_equal(
                    np.asarray(a.assigned), np.asarray(z.assigned)
                ), f"trial {trial}"
                assert np.array_equal(
                    np.asarray(a.nodes_needed),
                    np.asarray(z.nodes_needed),
                )
                assert int(a.unschedulable) == int(z.unschedulable)

    def test_priority_steers_away_from_preemptible_tiers(self):
        fields = dict(
            pod_requests=np.full((4, 1), 1.0, np.float32),
            pod_valid=np.ones(4, bool),
            pod_intolerant=np.zeros((4, 1), bool),
            pod_required=np.zeros((4, 1), bool),
            group_allocatable=np.full((2, 1), 8.0, np.float32),
            group_taints=np.zeros((2, 1), bool),
            group_labels=np.zeros((2, 1), bool),
        )
        inputs = B.BinPackInputs(
            **fields,
            pod_priority=np.array([0, 0, 500, 500], np.int32),
            group_tier=np.array([1, 0], np.int32),
        )
        import jax

        device = B.binpack(jax.device_put(inputs))
        host = binpack_numpy(inputs)
        # priority-0 pods keep first-feasible (the spot group);
        # priority-500 pods steer to the on-demand group
        assert np.asarray(device.assigned).tolist() == [0, 0, 1, 1]
        assert np.array_equal(
            np.asarray(device.assigned), np.asarray(host.assigned)
        )


    def test_large_preference_scores_survive_steering(self):
        """Regression (r6 review): soft-spread scores scale with live
        domain counts (magnitudes beyond a few thousand are routine),
        so steering must never clamp-and-compose them — a priority-0
        pod in a fleet that merely CARRIES the operands must assign
        exactly as if they were absent."""
        import jax

        fields = dict(
            pod_requests=np.full((1, 1), 1.0, np.float32),
            pod_valid=np.ones(1, bool),
            pod_intolerant=np.zeros((1, 1), bool),
            pod_required=np.zeros((1, 1), bool),
            group_allocatable=np.full((2, 1), 8.0, np.float32),
            group_taints=np.zeros((2, 1), bool),
            group_labels=np.zeros((2, 1), bool),
            pod_group_score=np.array([[-3000.0, -2500.0]], np.float32),
        )
        plain = B.BinPackInputs(**fields)
        carrying = B.BinPackInputs(
            **fields,
            pod_priority=np.zeros(1, np.int32),
            group_tier=np.array([0, 1], np.int32),
        )
        for solver in (
            lambda x: B.binpack(jax.device_put(x)),
            binpack_numpy,
        ):
            assert np.asarray(solver(plain).assigned).tolist() == [1]
            assert np.asarray(solver(carrying).assigned).tolist() == [1]

    def test_steer_is_lexicographically_senior_to_score(self):
        """A positive-priority pod leaves a preemptible group even when
        the preference score strongly favors it; the score still breaks
        ties among same-tier groups."""
        import jax

        inputs = B.BinPackInputs(
            pod_requests=np.full((1, 1), 1.0, np.float32),
            pod_valid=np.ones(1, bool),
            pod_intolerant=np.zeros((1, 1), bool),
            pod_required=np.zeros((1, 1), bool),
            group_allocatable=np.full((3, 1), 8.0, np.float32),
            group_taints=np.zeros((3, 1), bool),
            group_labels=np.zeros((3, 1), bool),
            pod_group_score=np.array(
                [[9000.0, -5000.0, -4000.0]], np.float32
            ),
            pod_priority=np.array([100], np.int32),
            group_tier=np.array([1, 0, 0], np.int32),
        )
        for solver in (
            lambda x: B.binpack(jax.device_put(x)),
            binpack_numpy,
        ):
            # spot group 0 loses despite its 9000 score; score picks
            # group 2 among the two on-demand groups
            assert np.asarray(solver(inputs).assigned).tolist() == [2]


class TestServiceSeam:
    def test_service_matches_mirror_and_caches_compiles(self):
        rng = np.random.default_rng(29)
        svc = SolverService(backend="xla")
        try:
            first = random_problem(rng, c=4, n=6, v=30, r=3)
            assert_outputs_equal(
                svc.preempt(first), preempt_numpy(first), "(service)"
            )
            misses = svc.stats.compile_cache_misses
            # same rungs (jittered sizes inside one bucket): no recompile
            again = random_problem(rng, c=5, n=6, v=28, r=3)
            assert_outputs_equal(
                svc.preempt(again), preempt_numpy(again), "(service 2)"
            )
            assert svc.stats.compile_cache_misses == misses
            assert svc.stats.preempt_dispatches == 2
        finally:
            svc.close()

    def test_empty_candidate_axis_short_circuits(self):
        svc = SolverService(backend="xla")
        try:
            rng = np.random.default_rng(31)
            inputs = random_problem(rng, c=1, n=2, v=4, r=2)
            import dataclasses

            empty = dataclasses.replace(
                inputs,
                pod_requests=inputs.pod_requests[:0],
                pod_priority=inputs.pod_priority[:0],
                pod_valid=inputs.pod_valid[:0],
                pod_node_forbidden=inputs.pod_node_forbidden[:0],
            )
            out = svc.preempt(empty)
            assert np.asarray(out.chosen_node).shape == (0,)
            assert svc.stats.dispatches == 0
        finally:
            svc.close()


# -- planner + engine ---------------------------------------------------------


def q(value):
    return Quantity.parse(str(value))


def make_node(name, labels=None, cpu="4", ready=True, annotations=None):
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels=dict(labels or {"pool": "a"}),
            annotations=dict(annotations or {}),
        ),
        spec=NodeSpec(),
        status=NodeStatus(
            allocatable={
                "cpu": q(cpu), "memory": q("8Gi"), "pods": q("16")
            },
            conditions=[
                NodeCondition("Ready", "True" if ready else "False")
            ],
        ),
    )


def make_pod(name, node=None, cpu="1", priority=None, annotations=None,
             priority_class=""):
    return Pod(
        metadata=ObjectMeta(
            name=name, annotations=dict(annotations or {})
        ),
        spec=PodSpec(
            node_name=node or "",
            priority=priority,
            priority_class_name=priority_class,
            containers=[
                Container(
                    requests={"cpu": q(cpu), "memory": q("1Gi")}
                )
            ],
        ),
    )


def storm_store(eviction_budget=None, preemptible=False):
    store = Store()
    store.create(
        MetricsProducer(
            metadata=ObjectMeta(name="pool"),
            spec=MetricsProducerSpec(
                pending_capacity=PendingCapacitySpec(
                    node_selector={"pool": "a"}, node_group_ref="grp"
                )
            ),
        )
    )
    store.create(
        ScalableNodeGroup(
            metadata=ObjectMeta(name="grp"),
            spec=ScalableNodeGroupSpec(
                replicas=2,
                type="FakeNodeGroup",
                id="grp",
                preemptible=preemptible,
                eviction_budget=eviction_budget,
            ),
        )
    )
    for name in ("n1", "n2"):
        store.create(make_node(name))
        for i in range(4):
            store.create(
                make_pod(f"{name}-batch-{i}", node=name, priority=0)
            )
    return store


def engine_for(store, clock=None, **config):
    svc = SolverService(backend="xla")
    engine = PreemptionEngine(
        store,
        svc,
        config=PreemptionConfig(
            min_candidate_priority=1, plan_interval_s=0.0, **config
        ),
        clock=clock,
    )
    return svc, engine


class TestEngine:
    def test_evicts_lowest_priority_to_admit_candidate(self):
        store = storm_store(eviction_budget=4)
        store.create(make_pod("critical", cpu="2", priority=1000))
        svc, engine = engine_for(store)
        try:
            plans = engine.plan()
            plan = plans[("default", "critical")]
            assert plan is not None and len(plan["evictions"]) == 2
            assert all(
                store.try_get("Pod", ns, name) is None
                for ns, name in plan["evictions"]
            )
        finally:
            svc.close()

    def test_budget_never_exceeded(self):
        store = storm_store(eviction_budget=1)
        store.create(make_pod("critical", cpu="2", priority=1000))
        svc, engine = engine_for(store)
        try:
            plans = engine.plan()
            # the plan needs 2 evictions but the budget allows 1:
            # DEFERRED, not trimmed — nothing was evicted
            assert plans[("default", "critical")] is None
            assert (
                sum(
                    1
                    for p in store.list("Pod")
                    if p.spec.node_name
                )
                == 8
            )
        finally:
            svc.close()

    def test_no_duplicate_evictions_across_conflicting_plans(self):
        store = storm_store(eviction_budget=8)
        store.create(make_pod("crit-a", cpu="2", priority=1000))
        store.create(make_pod("crit-b", cpu="2", priority=900))
        svc, engine = engine_for(store)
        try:
            plans = engine.plan()
            accepted = [p for p in plans.values() if p]
            evicted = [
                key for p in accepted for key in p["evictions"]
            ]
            assert len(evicted) == len(set(evicted)), (
                "one victim evicted twice"
            )
            # plans that share a target node defer; each accepted plan
            # holds a distinct node
            nodes = [p["node"] for p in accepted]
            assert len(nodes) == len(set(nodes))
        finally:
            svc.close()

    def test_do_not_disrupt_pod_never_evicted(self):
        store = storm_store(eviction_budget=8)
        for name in ("n1", "n2"):
            for i in range(4):
                pod = store.get("Pod", "default", f"{name}-batch-{i}")
                pod.metadata.annotations[
                    "karpenter.sh/do-not-disrupt"
                ] = "true"
                store.update(pod)
        store.create(make_pod("critical", cpu="2", priority=1000))
        svc, engine = engine_for(store)
        try:
            plans = engine.plan()
            assert plans[("default", "critical")] is None
            assert sum(
                1 for p in store.list("Pod") if p.spec.node_name
            ) == 8
        finally:
            svc.close()

    def test_candidate_hold_prevents_amplification(self):
        store = storm_store(eviction_budget=8)
        store.create(make_pod("critical", cpu="2", priority=1000))
        svc, engine = engine_for(store)
        try:
            first = engine.plan()
            assert first[("default", "critical")] is not None
            # the candidate stays pending (nothing binds it here): the
            # hold keeps the next rounds from evicting MORE pods for it
            assert engine.plan() == {}
            assert sum(
                1 for p in store.list("Pod") if p.spec.node_name
            ) == 6
        finally:
            svc.close()

    def test_partial_actuation_is_not_an_accepted_plan(self):
        """Regression (r6 review): a store conflict vetoing part of an
        eviction set must not record the plan as accepted — the
        candidate is re-planned promptly instead of sitting out a full
        hold with insufficient freed capacity."""
        store = storm_store(eviction_budget=4)
        store.create(make_pod("critical", cpu="2", priority=1000))
        svc, engine = engine_for(store)
        real_delete = store.delete
        vetoed = {"n": 0}

        def flaky_delete(kind, namespace=None, name=None):
            if name == "n1-batch-1" and vetoed["n"] == 0:
                vetoed["n"] += 1
                raise RuntimeError("conflict")
            return real_delete(kind, namespace, name)

        store.delete = flaky_delete
        try:
            plans = engine.plan()
            assert plans[("default", "critical")] is None
            # the pod that DID evict stays charged; the candidate is
            # free to re-plan immediately
            assert ("default", "critical") not in engine._candidate_holds
            again = engine.plan(engine.clock() + 1.0)
            assert again[("default", "critical")] is not None
        finally:
            store.delete = real_delete
            svc.close()

    def test_ungrouped_nodes_budget_independently(self):
        from karpenter_tpu.preemption.engine import PreemptionEngine

        assert PreemptionEngine._budget_key(
            ("default", "pool", "grp"), "n1"
        ) == ("default", "grp")
        assert PreemptionEngine._budget_key(None, "n1") != (
            PreemptionEngine._budget_key(None, "n2")
        )

    def test_consolidation_coordination_both_ways(self):
        from karpenter_tpu.consolidation import ConsolidationEngine

        store = storm_store(eviction_budget=8)
        store.create(make_pod("critical", cpu="2", priority=1000))
        svc = SolverService(backend="xla")
        try:
            consolidation = ConsolidationEngine(
                store, solver_service=svc
            )
            engine = PreemptionEngine(
                store,
                svc,
                consolidation=consolidation,
                config=PreemptionConfig(
                    min_candidate_priority=1, plan_interval_s=0.0
                ),
            )
            consolidation.node_guard = engine.active_nodes
            # consolidation owns n1: preemption must plan around it
            consolidation._in_flight["n1"] = type(
                "S", (), {"node": "n1", "group": ("default", "x", "grp"),
                          "phase": "cordoned", "since": 0.0}
            )()
            plans = engine.plan()
            plan = plans[("default", "critical")]
            assert plan is not None and plan["node"] == "n2"
            # ...and the preemption hold guards n2 from consolidation
            assert "n2" in engine.active_nodes()
            view_node = [
                nv
                for nv in __import__(
                    "karpenter_tpu.consolidation.planner",
                    fromlist=["cluster_view"],
                ).cluster_view(store).nodes
                if nv.name == "n2"
            ][0]
            assert not consolidation._eligible(
                view_node, now=1e9,
                guarded=consolidation.node_guard(),
            )
        finally:
            svc.close()


class TestPriorityPlumbing:
    def test_effective_priority_resolution(self):
        assert effective_priority(make_pod("p", priority=7)) == 7
        assert (
            effective_priority(
                make_pod("p", priority_class="system-node-critical")
            )
            == 2_000_001_000
        )
        # the fleet default covers pods NAMING an unknown class only;
        # class-less pods stay at 0 (a nonzero knob must not lift the
        # whole fleet into nonzero-priority encoding)
        assert (
            effective_priority(
                make_pod("p", priority_class="important"), default=42
            )
            == 42
        )
        assert effective_priority(make_pod("p"), default=42) == 0

    def test_capacity_tier_labels(self):
        assert capacity_tier_of({"karpenter.sh/capacity-type": "spot"}) == 1
        assert capacity_tier_of({"cloud.google.com/gke-spot": "true"}) == 1
        assert capacity_tier_of({"pool": "a"}) == 0
        assert capacity_tier_of({("pool", "a"), ("x", "y")}) == 0

    def test_encoder_emits_priority_and_tier_only_when_present(self):
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            encode_snapshot,
            group_profile,
        )
        from karpenter_tpu.store.columnar import snapshot_from_pods

        spot = make_node(
            "s1", labels={"pool": "a", "karpenter.sh/capacity-type": "spot"}
        )
        plain = make_node("p1", labels={"pool": "b"})
        profiles_plain = [group_profile([plain], {"pool": "b"})]
        profiles_spot = [group_profile([spot], {"pool": "a"})]

        flat = snapshot_from_pods([make_pod("w", cpu="1")])
        inputs = encode_snapshot(flat, profiles_plain)
        assert inputs.pod_priority is None
        assert inputs.group_tier is None

        prioritized = snapshot_from_pods(
            [make_pod("w", cpu="1", priority=100)]
        )
        inputs = encode_snapshot(prioritized, profiles_spot)
        assert inputs.pod_priority is not None
        assert int(inputs.pod_priority[0]) == 100
        assert inputs.group_tier is not None
        assert int(inputs.group_tier[0]) == 1

    def test_priority_splits_dedup_rows(self):
        from karpenter_tpu.store.columnar import snapshot_from_pods

        snap = snapshot_from_pods(
            [
                make_pod("a", cpu="1", priority=0),
                make_pod("b", cpu="1", priority=0),
                make_pod("c", cpu="1", priority=50),
            ]
        )
        # identical specs at two priorities: two distinct shapes
        assert len(snap.dedup_idx) == 2
        assert sorted(snap.dedup_weight.tolist()) == [1, 2]
