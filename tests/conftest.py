"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Real-TPU runs (bench.py, the driver) use the real backend; tests exercise
multi-chip sharding logic on virtual CPU devices per the build environment's
contract.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
