"""Test harness: force an 8-device virtual CPU mesh before any backend init.

Real-TPU runs (bench.py, the driver) use the real backend; tests exercise
multi-chip sharding logic on virtual CPU devices per the build environment's
contract.

Environment gotcha: this container's sitecustomize (axon) imports jax at
interpreter startup with JAX_PLATFORMS=axon, so mutating os.environ here is
too late for backend selection — and initializing the axon PJRT client from
a test process hangs. jax.config.update('jax_platforms', ...) before the
first backend init is the reliable switch; XLA_FLAGS is still read lazily at
CPU client creation, so setting it here works.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from karpenter_tpu.utils.backend import force_virtual_cpu  # noqa: E402

force_virtual_cpu(8)
