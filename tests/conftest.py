"""Test harness: force an 8-device virtual CPU mesh before any backend init.

Real-TPU runs (bench.py, the driver) use the real backend; tests exercise
multi-chip sharding logic on virtual CPU devices per the build environment's
contract.

Environment gotcha: this container's sitecustomize (axon) imports jax at
interpreter startup with JAX_PLATFORMS=axon, so mutating os.environ here is
too late for backend selection — and initializing the axon PJRT client from
a test process hangs. jax.config.update('jax_platforms', ...) before the
first backend init is the reliable switch; XLA_FLAGS is still read lazily at
CPU client creation, so setting it here works.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from karpenter_tpu.utils.backend import force_virtual_cpu  # noqa: E402

if os.environ.get("KARPENTER_TEST_REAL_BACKEND"):
    # Opt-in escape hatch for TPU hosts: leave the real backend in place so
    # the @skipUnless(tpu) cases (e.g. tests/test_pallas_binpack.py's
    # compiled-Mosaic equality tests) actually run. Only use with a narrow
    # test selection — the full suite assumes the 8-device CPU mesh.
    pass
else:
    force_virtual_cpu(8)


def pytest_collection_modifyitems(config, items):
    """Randomize test order (the reference's battletest runs randomized,
    Makefile:25-31; pytest-randomly is not in this image, so the shuffle
    lives here). Opt-in via KARPENTER_TEST_SHUFFLE=<seed> ('random' picks
    one); the seed is printed so any ordering failure is reproducible."""
    seed = os.environ.get("KARPENTER_TEST_SHUFFLE")
    if not seed:
        return
    import random

    if seed == "random":
        seed = str(random.SystemRandom().randrange(2**31))
    print(f"\n[conftest] shuffling test order with seed {seed}")
    random.Random(int(seed)).shuffle(items)
