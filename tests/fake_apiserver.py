"""Minimal in-memory kube-apiserver for KubeStore tests.

Implements the REST surface KubeClient exercises — typed collections
(list/watch with streaming chunked events), namespaced CRUD, merge-patch
/status, the /scale subresource, coordination.k8s.io leases with
resourceVersion conflict checks — the envtest role (reference:
pkg/test/environment/local.go boots a REAL apiserver; this double speaks
just enough of the same protocol).
"""

from __future__ import annotations

import json
import queue
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

# plural -> kind (everything the client speaks, incl. leases)
PLURALS = {
    "horizontalautoscalers": "HorizontalAutoscaler",
    "metricsproducers": "MetricsProducer",
    "scalablenodegroups": "ScalableNodeGroup",
    "pods": "Pod",
    "nodes": "Node",
    "namespaces": "Namespace",
    "leases": "Lease",
    # a standard scalable workload kind the framework does NOT model:
    # exercises discovery-based scale-target resolution (an HA pointing
    # its scaleTargetRef at a Deployment, reference autoscaler.go:196-237)
    "deployments": "Deployment",
}

# API discovery documents (GET /apis, /api/v1, /apis/<group>/<version>):
# what KubeClient.resolve_kind walks to map an unknown kind to its
# (group-version, plural) — the RESTMapper-over-discovery pattern.
API_GROUPS = {
    "autoscaling.karpenter.sh": ["v1alpha1"],
    "apps": ["v1"],
    "coordination.k8s.io": ["v1"],
}
API_RESOURCES = {
    "api/v1": [
        ("pods", "Pod", True),
        ("nodes", "Node", False),
        ("namespaces", "Namespace", False),
    ],
    "apis/autoscaling.karpenter.sh/v1alpha1": [
        ("horizontalautoscalers", "HorizontalAutoscaler", True),
        ("metricsproducers", "MetricsProducer", True),
        ("scalablenodegroups", "ScalableNodeGroup", True),
        ("scalablenodegroups/scale", "Scale", True),
    ],
    "apis/apps/v1": [
        ("deployments", "Deployment", True),
        ("deployments/scale", "Scale", True),
    ],
    "apis/coordination.k8s.io/v1": [("leases", "Lease", True)],
}

_PATH_RE = re.compile(
    r"^/(?:api/v1|apis/[^/]+/[^/]+)"
    r"(?:/namespaces/(?P<ns>[^/]+))?"
    r"/(?P<plural>[^/?]+)"
    r"(?:/(?P<name>[^/?]+))?"
    r"(?:/(?P<sub>status|scale))?$"
)


def _merge_patch(target, patch):
    """RFC 7386 JSON merge-patch: null deletes a key, maps merge
    recursively, everything else replaces — the semantics a real apiserver
    applies to application/merge-patch+json (so stale-key deletion via
    explicit nulls is actually exercised here)."""
    if not isinstance(patch, dict):
        return patch
    out = dict(target) if isinstance(target, dict) else {}
    for key, value in patch.items():
        if value is None:
            out.pop(key, None)
        else:
            out[key] = _merge_patch(out.get(key), value)
    return out


class FakeApiServer:
    # bounded watch-event history, the etcd watch window analog: events
    # older than this fall off and a watch resuming from before the
    # horizon gets the real apiserver's "too old resource version" 410
    HISTORY_LIMIT = 1024

    def __init__(self, history_limit: Optional[int] = None):
        self._lock = threading.Lock()
        # explicit 0 means a zero-length window (every resume 410s)
        self._history_limit = (
            self.HISTORY_LIMIT if history_limit is None else history_limit
        )
        # (rv, plural, event dict) of every broadcast, newest last
        self._history: List[Tuple[int, str, dict]] = []
        # rv of the newest DISCARDED event: watches from at/below this
        # cannot be replayed losslessly -> 410 (API concepts: "410 Gone:
        # the requested resource version is no longer available")
        self._compacted_rv = 0
        self.list_pages_served = 0  # chunked-list pages (tests assert)
        # chunked-list snapshots: like the real apiserver, every page of
        # one paginated LIST serves from the FIRST page's snapshot (same
        # items, same collection rv), or concurrent writes would skip /
        # duplicate objects across pages
        self._list_snapshots: Dict[str, Tuple[list, str]] = {}
        self._snapshot_seq = 0
        self._objects: Dict[Tuple[str, str, str], dict] = {}
        self._rv = 0
        self._watchers: List[Tuple[str, "queue.Queue"]] = []
        self._server: Optional[ThreadingHTTPServer] = None
        self.port = 0

    # -- state helpers -----------------------------------------------------

    def put_object(self, plural: str, doc: dict, event: str = "ADDED") -> dict:
        """Test-side direct mutation (simulates another client)."""
        with self._lock:
            return self._store(plural, doc, event)

    def _store(self, plural: str, doc: dict, event: str) -> dict:
        meta = doc.setdefault("metadata", {})
        ns = meta.setdefault("namespace", "default")
        name = meta["name"]
        self._rv += 1
        meta["resourceVersion"] = str(self._rv)
        meta.setdefault("uid", f"uid-fake-{self._rv}")
        doc.setdefault("kind", PLURALS[plural])
        self._objects[(plural, ns, name)] = doc
        self._broadcast(plural, event, doc)
        return doc

    def delete_object(self, plural: str, ns: str, name: str) -> Optional[dict]:
        with self._lock:
            doc = self._objects.pop((plural, ns, name), None)
            if doc is not None:
                self._rv += 1
                # the DELETED event carries the final object state AT THE
                # DELETION's resourceVersion (API concepts: a delete bumps
                # rv like any write; clients advance their watch watermark
                # from it)
                doc["metadata"]["resourceVersion"] = str(self._rv)
                self._broadcast(plural, "DELETED", doc)
            return doc

    def _broadcast(self, plural: str, event: str, doc: dict) -> None:
        rv = int(doc["metadata"]["resourceVersion"])
        self._history.append(
            (rv, plural, {"type": event, "object": json.loads(json.dumps(doc))})
        )
        while len(self._history) > self._history_limit:
            self._compacted_rv = self._history.pop(0)[0]
        for want, q in list(self._watchers):
            if want == plural:
                q.put({"type": event, "object": doc})

    @staticmethod
    def discovery_doc(path: str) -> Optional[dict]:
        """The discovery document for a path, or None when the path is a
        resource request (handled by the CRUD machinery)."""
        path = path.strip("/")
        if path == "apis":
            return {
                "kind": "APIGroupList",
                "groups": [
                    {
                        "name": group,
                        "versions": [
                            {"groupVersion": f"{group}/{v}", "version": v}
                            for v in versions
                        ],
                        "preferredVersion": {
                            "groupVersion": f"{group}/{versions[0]}",
                            "version": versions[0],
                        },
                    }
                    for group, versions in API_GROUPS.items()
                ],
            }
        resources = API_RESOURCES.get(path)
        if resources is None:
            return None
        return {
            "kind": "APIResourceList",
            "groupVersion": path.split("apis/")[-1],
            "resources": [
                {"name": name, "kind": kind, "namespaced": namespaced}
                for name, kind, namespaced in resources
            ],
        }

    def objects(self, plural: str) -> List[dict]:
        with self._lock:
            return [
                json.loads(json.dumps(d))
                for (p, _, _), d in self._objects.items()
                if p == plural
            ]

    # -- server ------------------------------------------------------------

    def start(self) -> int:
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _send_json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length", "0"))
                return json.loads(self.rfile.read(length)) if length else {}

            def _match(self):
                parts = urlsplit(self.path)
                m = _PATH_RE.match(parts.path)
                if m is None or m.group("plural") not in PLURALS:
                    self._send_json(404, {"message": "not found"})
                    return None
                return m, parse_qs(parts.query)

            def do_GET(self):  # noqa: N802
                discovery = fake.discovery_doc(urlsplit(self.path).path)
                if discovery is not None:
                    return self._send_json(200, discovery)
                matched = self._match()
                if matched is None:
                    return
                m, query = matched
                plural, ns, name = m.group("plural"), m.group("ns"), m.group("name")
                if name is None:
                    if query.get("watch"):
                        since = int(
                            (query.get("resourceVersion") or ["0"])[0]
                        )
                        return self._serve_watch(plural, since)
                    limit = int((query.get("limit") or ["0"])[0])
                    token = (query.get("continue") or [""])[0]
                    with fake._lock:
                        if limit > 0 and token:
                            # later page: serve from the FIRST page's
                            # snapshot (real-apiserver semantics)
                            snap_id, _, start_s = token.partition(":")
                            snapshot = fake._list_snapshots.get(snap_id)
                            if snapshot is None or not start_s.isdigit():
                                # expired/unknown token: the real
                                # apiserver's 410 Expired, not a crashed
                                # handler thread
                                return self._send_json(
                                    410,
                                    {
                                        "kind": "Status",
                                        "code": 410,
                                        "reason": "Expired",
                                        "message": "continue token expired",
                                    },
                                )
                            items, rv = snapshot
                            start = int(start_s)
                        else:
                            items = [
                                json.loads(json.dumps(d))
                                for (p, _, _), d in sorted(
                                    fake._objects.items()
                                )
                                if p == plural
                            ]
                            rv = str(fake._rv)
                            start = 0
                            if limit > 0:
                                fake._snapshot_seq += 1
                                snap_id = f"s{fake._snapshot_seq}"
                                fake._list_snapshots[snap_id] = (items, rv)
                                # abandoned paginations must not leak:
                                # keep only the most recent snapshots
                                while len(fake._list_snapshots) > 8:
                                    fake._list_snapshots.pop(
                                        next(iter(fake._list_snapshots))
                                    )
                        meta = {"resourceVersion": rv}
                        if limit > 0:
                            fake.list_pages_served += 1
                            chunk = items[start : start + limit]
                            if start + limit < len(items):
                                meta["continue"] = (
                                    f"{snap_id}:{start + limit}"
                                )
                            else:
                                fake._list_snapshots.pop(snap_id, None)
                            items = chunk
                    return self._send_json(
                        200,
                        {
                            "kind": f"{PLURALS[plural]}List",
                            "metadata": meta,
                            "items": items,
                        },
                    )
                with fake._lock:
                    doc = fake._objects.get((plural, ns or "default", name))
                if doc is None:
                    return self._send_json(404, {"message": "not found"})
                if m.group("sub") == "scale":
                    return self._send_json(
                        200,
                        {
                            "apiVersion": "autoscaling/v1",
                            "kind": "Scale",
                            "spec": {
                                "replicas": doc.get("spec", {}).get("replicas")
                            },
                            "status": {
                                "replicas": doc.get("status", {}).get(
                                    "replicas", 0
                                )
                                or 0
                            },
                        },
                    )
                return self._send_json(200, doc)

            def _serve_watch(self, plural: str, since: int):
                q: "queue.Queue" = queue.Queue()
                with fake._lock:
                    expired = since and since < fake._compacted_rv
                    if not expired:
                        if since:
                            # replay the EVENT history after `since` —
                            # including DELETED events, which an
                            # object-state replay would silently lose
                            # (the resumed client would keep deleted
                            # objects in its mirror forever)
                            for rv, p, event in fake._history:
                                if p == plural and rv > since:
                                    q.put(event)
                        else:
                            # rv=0: "any point is fine" — serve the
                            # current state as synthetic ADDEDs
                            for (p, _, _), doc in fake._objects.items():
                                if p == plural:
                                    q.put({"type": "ADDED", "object": doc})
                        fake._watchers.append((plural, q))
                if expired:
                    # watch window expired: the real apiserver delivers
                    # an IN-STREAM ERROR event carrying a 410 Status
                    # ("too old resource version"), terminates the
                    # chunked body, and closes — NOT an HTTP error
                    # (API concepts: Efficient detection of changes)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    line = (
                        json.dumps(
                            {
                                "type": "ERROR",
                                "object": {
                                    "kind": "Status",
                                    "code": 410,
                                    "reason": "Expired",
                                    "message": (
                                        f"too old resource version: "
                                        f"{since}"
                                    ),
                                },
                            }
                        )
                        + "\n"
                    ).encode()
                    self.wfile.write(
                        f"{len(line):x}\r\n".encode() + line + b"\r\n"
                    )
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                    self.close_connection = True
                    return
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    while not getattr(fake, "_closing", False):
                        try:
                            event = q.get(timeout=0.2)
                        except queue.Empty:
                            continue
                        line = (json.dumps(event) + "\n").encode()
                        self.wfile.write(
                            f"{len(line):x}\r\n".encode() + line + b"\r\n"
                        )
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    with fake._lock:
                        if (plural, q) in fake._watchers:
                            fake._watchers.remove((plural, q))

            def do_POST(self):  # noqa: N802
                matched = self._match()
                if matched is None:
                    return
                m, _ = matched
                plural, ns = m.group("plural"), m.group("ns") or "default"
                doc = self._body()
                doc.setdefault("metadata", {}).setdefault("namespace", ns)
                name = doc["metadata"]["name"]
                with fake._lock:
                    if (plural, ns, name) in fake._objects:
                        return self._send_json(
                            409, {"message": "already exists"}
                        )
                    stored = fake._store(plural, doc, "ADDED")
                return self._send_json(201, stored)

            def do_PUT(self):  # noqa: N802
                matched = self._match()
                if matched is None:
                    return
                m, _ = matched
                plural, ns, name = (
                    m.group("plural"),
                    m.group("ns") or "default",
                    m.group("name"),
                )
                doc = self._body()
                with fake._lock:
                    stored = fake._objects.get((plural, ns, name))
                    if stored is None:
                        return self._send_json(404, {"message": "not found"})
                    if m.group("sub") == "scale":
                        stored = json.loads(json.dumps(stored))
                        stored.setdefault("spec", {})["replicas"] = doc.get(
                            "spec", {}
                        ).get("replicas")
                        updated = fake._store(plural, stored, "MODIFIED")
                        return self._send_json(200, updated)
                    incoming_rv = doc.get("metadata", {}).get(
                        "resourceVersion"
                    )
                    if incoming_rv and incoming_rv != stored["metadata"][
                        "resourceVersion"
                    ]:
                        return self._send_json(
                            409, {"message": "resourceVersion conflict"}
                        )
                    doc.setdefault("metadata", {})["namespace"] = ns
                    doc["metadata"]["name"] = name
                    updated = fake._store(plural, doc, "MODIFIED")
                return self._send_json(200, updated)

            def do_PATCH(self):  # noqa: N802
                matched = self._match()
                if matched is None:
                    return
                m, _ = matched
                plural, ns, name = (
                    m.group("plural"),
                    m.group("ns") or "default",
                    m.group("name"),
                )
                patch = self._body()
                with fake._lock:
                    stored = fake._objects.get((plural, ns, name))
                    if stored is None:
                        return self._send_json(404, {"message": "not found"})
                    stored = json.loads(json.dumps(stored))
                    if m.group("sub") == "status":
                        stored["status"] = _merge_patch(
                            stored.get("status", {}), patch.get("status", {})
                        )
                    else:
                        stored = _merge_patch(stored, patch)
                    updated = fake._store(plural, stored, "MODIFIED")
                return self._send_json(200, updated)

            def do_DELETE(self):  # noqa: N802
                matched = self._match()
                if matched is None:
                    return
                m, _ = matched
                plural, ns, name = (
                    m.group("plural"),
                    m.group("ns") or "default",
                    m.group("name"),
                )
                doc = fake.delete_object(plural, ns, name)
                if doc is None:
                    return self._send_json(404, {"message": "not found"})
                return self._send_json(200, {"status": "Success"})

            def log_message(self, *args):
                pass

        self._closing = False
        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()
        return self.port

    def stop(self) -> None:
        self._closing = True
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
