"""PendingCapacity producer e2e: the signal the reference stubbed
(pendingcapacity/producer.go:29-31), implemented per DESIGN.md "Pending
Pods" — pending pods drive exactly one node group's scale-up, through the
full pipeline: solver -> gauge -> autoscaler -> provider."""

import pytest

from karpenter_tpu.api.core import (
    Container,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Taint,
    Toleration,
    resource_list,
)
from karpenter_tpu.api.horizontalautoscaler import (
    CrossVersionObjectReference,
    HorizontalAutoscaler,
    HorizontalAutoscalerSpec,
    Metric,
    MetricTarget,
    PrometheusMetricSource,
)
from karpenter_tpu.api.metricsproducer import (
    MetricsProducer,
    MetricsProducerSpec,
    PendingCapacitySpec,
)
from karpenter_tpu.api.scalablenodegroup import (
    ScalableNodeGroup,
    ScalableNodeGroupSpec,
)
from karpenter_tpu.cloudprovider.fake import FakeFactory
from karpenter_tpu.runtime import KarpenterRuntime


class FakeClock:
    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


@pytest.fixture
def env():
    clock = FakeClock()
    provider = FakeFactory()
    runtime = KarpenterRuntime(cloud_provider_factory=provider, clock=clock)
    return runtime, provider, clock


def ready_node(name, labels, cpu="4", memory="8Gi", pods="16", taints=()):
    return Node(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        spec=NodeSpec(taints=list(taints)),
        status=NodeStatus(
            allocatable=resource_list(cpu=cpu, memory=memory, pods=pods),
            conditions=[NodeCondition("Ready", "True")],
        ),
    )


def pending_pod(name, cpu="1", memory="1Gi", node_selector=None, tolerations=()):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(
            node_name="",  # unschedulable
            containers=[Container(requests=resource_list(cpu=cpu, memory=memory))],
            node_selector=dict(node_selector or {}),
            tolerations=list(tolerations),
        ),
    )


def pending_mp(name, selector):
    return MetricsProducer(
        metadata=ObjectMeta(name=name),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(node_selector=dict(selector))
        ),
    )


class TestPendingCapacitySignal:
    def test_nodes_needed_for_pending_pods(self, env):
        runtime, provider, clock = env
        selector = {"group": "a"}
        runtime.store.create(ready_node("n1", selector, cpu="4", memory="8Gi"))
        # 8 pods of 2 cpu each -> 2 per node -> 4 nodes
        for i in range(8):
            runtime.store.create(pending_pod(f"p{i}", cpu="2", memory="1Gi"))
        runtime.store.create(pending_mp("group-a", selector))

        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.pending_pods == 8
        assert mp.status.pending_capacity.additional_nodes_needed == 4
        assert mp.status.pending_capacity.lp_lower_bound == 4
        assert mp.status.pending_capacity.unschedulable_pods == 0
        assert mp.status_conditions().is_happy()
        assert (
            runtime.registry.gauge(
                "pending_capacity", "additional_nodes_needed"
            ).get("group-a", "default")
            == 4.0
        )

    def test_each_pod_drives_one_group(self, env):
        """DESIGN.md: only a single node group scales up per pod."""
        runtime, provider, clock = env
        runtime.store.create(ready_node("n1", {"group": "a"}))
        runtime.store.create(ready_node("n2", {"group": "b"}))
        runtime.store.create(pending_pod("p0", cpu="1"))
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        runtime.store.create(pending_mp("group-b", {"group": "b"}))

        runtime.manager.reconcile_all()
        a = runtime.store.get("MetricsProducer", "default", "group-a")
        b = runtime.store.get("MetricsProducer", "default", "group-b")
        total = (
            a.status.pending_capacity.pending_pods
            + b.status.pending_capacity.pending_pods
        )
        assert total == 1  # not double-counted

    def test_node_selector_routes_pods(self, env):
        runtime, provider, clock = env
        runtime.store.create(ready_node("n1", {"group": "a", "disk": "ssd"}))
        runtime.store.create(ready_node("n2", {"group": "b"}))
        runtime.store.create(
            pending_pod("needs-ssd", node_selector={"disk": "ssd"})
        )
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        runtime.store.create(pending_mp("group-b", {"group": "b"}))

        runtime.manager.reconcile_all()
        a = runtime.store.get("MetricsProducer", "default", "group-a")
        b = runtime.store.get("MetricsProducer", "default", "group-b")
        assert a.status.pending_capacity.pending_pods == 1
        assert b.status.pending_capacity.pending_pods == 0

    def test_taints_respected(self, env):
        runtime, provider, clock = env
        taint = Taint(key="dedicated", value="ml", effect="NoSchedule")
        runtime.store.create(
            ready_node("n1", {"group": "a"}, taints=[taint])
        )
        runtime.store.create(ready_node("n2", {"group": "b"}))
        runtime.store.create(pending_pod("intolerant"))
        runtime.store.create(
            pending_pod(
                "tolerant",
                tolerations=[
                    Toleration(key="dedicated", value="ml", effect="NoSchedule")
                ],
            )
        )
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        runtime.store.create(pending_mp("group-b", {"group": "b"}))

        runtime.manager.reconcile_all()
        a = runtime.store.get("MetricsProducer", "default", "group-a")
        b = runtime.store.get("MetricsProducer", "default", "group-b")
        # tolerant pod -> first feasible group (a); intolerant pod -> b
        assert a.status.pending_capacity.pending_pods == 1
        assert b.status.pending_capacity.pending_pods == 1

    def test_partial_batch_still_sees_all_groups(self, env):
        """Single-scale-up must hold even when only ONE producer is due:
        the solve always spans every pendingCapacity MP in the store."""
        runtime, provider, clock = env
        runtime.store.create(ready_node("n1", {"group": "a"}))
        runtime.store.create(ready_node("n2", {"group": "b"}))
        runtime.store.create(pending_pod("p0"))
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        runtime.store.create(pending_mp("group-b", {"group": "b"}))
        runtime.manager.reconcile_all()

        # only group-b becomes due (watch event via touch); group-a is not
        b = runtime.store.get("MetricsProducer", "default", "group-b")
        runtime.store.update(b)  # touch -> watch -> due
        runtime.manager.reconcile_all()
        b = runtime.store.get("MetricsProducer", "default", "group-b")
        # the pod is already absorbed by group-a; a partial solve over only
        # group-b must NOT claim it
        assert b.status.pending_capacity.pending_pods == 0
        assert b.status.pending_capacity.unschedulable_pods == 0

    def test_prefer_no_schedule_taint_is_soft(self, env):
        runtime, provider, clock = env
        soft = Taint(key="flaky", value="", effect="PreferNoSchedule")
        runtime.store.create(ready_node("n1", {"group": "a"}, taints=[soft]))
        runtime.store.create(pending_pod("p0"))
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.pending_pods == 1  # soft ≠ blocked

    def test_missing_pods_allocatable_defaults(self, env):
        runtime, provider, clock = env
        node = Node(
            metadata=ObjectMeta(name="n1", labels={"group": "a"}),
            status=NodeStatus(
                allocatable=resource_list(cpu="4", memory="8Gi"),  # no 'pods'
                conditions=[NodeCondition("Ready", "True")],
            ),
        )
        runtime.store.create(node)
        runtime.store.create(pending_pod("p0"))
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.pending_pods == 1

    def test_poisoned_producer_fails_only_its_own_row(self, env):
        """Blast-radius isolation in the batched solve: one producer whose
        spec blows up during encoding (node_selector=None — validation is
        a no-op for pendingCapacity, matching the reference's
        metricsproducer_validation.go:85-87, and real-cluster informers
        deliver whatever the apiserver holds) must fail ONLY itself; every
        healthy producer still solves and updates (mirrors the
        reference's per-object containment, controller.go:85-91)."""
        runtime, provider, clock = env
        runtime.store.create(ready_node("n1", {"group": "a"}, cpu="4"))
        for i in range(4):
            runtime.store.create(pending_pod(f"p{i}", cpu="2", memory="1Gi"))
        runtime.store.create(pending_mp("healthy", {"group": "a"}))
        poisoned = MetricsProducer(
            metadata=ObjectMeta(name="poisoned"),
            spec=MetricsProducerSpec(
                pending_capacity=PendingCapacitySpec(node_selector=None)
            ),
        )
        runtime.store.create(poisoned)

        runtime.manager.reconcile_all()

        healthy = runtime.store.get("MetricsProducer", "default", "healthy")
        assert healthy.status.pending_capacity is not None
        assert healthy.status.pending_capacity.pending_pods == 4
        assert healthy.status.pending_capacity.additional_nodes_needed == 2
        assert healthy.status_conditions().is_happy()

        bad = runtime.store.get("MetricsProducer", "default", "poisoned")
        assert not bad.status_conditions().is_happy()
        assert bad.status.pending_capacity is None  # no placeholder solve

    def test_unschedulable_pod_reported(self, env):
        runtime, provider, clock = env
        runtime.store.create(ready_node("n1", {"group": "a"}, cpu="2"))
        runtime.store.create(pending_pod("huge", cpu="64"))
        runtime.store.create(pending_mp("group-a", {"group": "a"}))
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.pending_pods == 0
        assert mp.status.pending_capacity.unschedulable_pods == 1


class TestScaleFromZero:
    """nodeGroupRef + provider NodeTemplate: a pool with NO live nodes
    still gets a correct additional-nodes signal — the gap every
    pending-pods autoscaler without instance metadata has (the profile
    docstring's admitted limitation, now closed)."""

    def _sng(self, name):
        from karpenter_tpu.api.scalablenodegroup import (
            ScalableNodeGroup,
            ScalableNodeGroupSpec,
        )

        return ScalableNodeGroup(
            metadata=ObjectMeta(name=name),
            spec=ScalableNodeGroupSpec(
                type="AWSEC2AutoScalingGroup", id=f"asg-{name}"
            ),
        )

    def _template(self, cpu="4", memory="8Gi", labels=None, taints=()):
        from karpenter_tpu.cloudprovider import NodeTemplate

        return NodeTemplate(
            allocatable=resource_list(cpu=cpu, memory=memory),
            labels=dict(labels or {}),
            taints=list(taints),
        )

    def _mp_with_ref(self, name, selector, ref):
        return MetricsProducer(
            metadata=ObjectMeta(name=name),
            spec=MetricsProducerSpec(
                pending_capacity=PendingCapacitySpec(
                    node_selector=dict(selector), node_group_ref=ref
                )
            ),
        )

    def test_empty_group_profiles_from_template(self, env):
        runtime, provider, clock = env
        runtime.store.create(self._sng("pool-a"))
        provider.node_templates["asg-pool-a"] = self._template(
            cpu="4", memory="8Gi"
        )
        # NO nodes exist; 6 pods of 2cpu -> 2 per 4-cpu template node
        for i in range(6):
            runtime.store.create(pending_pod(f"p{i}", cpu="2", memory="1Gi"))
        runtime.store.create(
            self._mp_with_ref("zero", {"group": "a"}, "pool-a")
        )
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "zero")
        assert mp.status.pending_capacity.pending_pods == 6
        assert mp.status.pending_capacity.additional_nodes_needed == 3
        assert mp.status.pending_capacity.unschedulable_pods == 0

    def test_live_nodes_win_over_template(self, env):
        runtime, provider, clock = env
        runtime.store.create(self._sng("pool-a"))
        # template says 64 cpu, but the LIVE node is 4 cpu: observed truth
        provider.node_templates["asg-pool-a"] = self._template(cpu="64")
        runtime.store.create(
            ready_node("n1", {"group": "a"}, cpu="4", memory="8Gi")
        )
        for i in range(4):
            runtime.store.create(pending_pod(f"p{i}", cpu="2", memory="1Gi"))
        runtime.store.create(
            self._mp_with_ref("live", {"group": "a"}, "pool-a")
        )
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "live")
        # 2 per live 4-cpu node, NOT 32 per template node
        assert mp.status.pending_capacity.additional_nodes_needed == 2

    def test_template_taints_and_labels_respected(self, env):
        from karpenter_tpu.api.core import Taint, Toleration

        runtime, provider, clock = env
        runtime.store.create(self._sng("pool-t"))
        provider.node_templates["asg-pool-t"] = self._template(
            cpu="8",
            labels={"disk": "ssd"},
            taints=[Taint(key="tpu", value="true", effect="NoSchedule")],
        )
        # intolerant pod: unschedulable even though cpu fits
        runtime.store.create(pending_pod("blocked", cpu="1"))
        # tolerating pod with a selector the template labels satisfy
        tolerating = pending_pod(
            "ok",
            cpu="1",
            node_selector={"disk": "ssd"},
            tolerations=[
                Toleration(key="tpu", operator="Equal", value="true")
            ],
        )
        runtime.store.create(tolerating)
        runtime.store.create(self._mp_with_ref("t", {"group": "t"}, "pool-t"))
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "t")
        assert mp.status.pending_capacity.pending_pods == 1  # only 'ok'
        assert mp.status.pending_capacity.unschedulable_pods == 1
        assert mp.status.pending_capacity.additional_nodes_needed == 1

    def test_template_resolution_is_ttl_cached(self, env):
        """Idle ticks must not pay a provider call per empty group: the
        resolver caches by (namespace, ref) within template_cache_ttl."""
        runtime, provider, clock = env
        calls = []
        real = provider.node_group_for

        def counting(spec):
            calls.append(spec.id)
            return real(spec)

        provider.node_group_for = counting
        runtime.store.create(self._sng("pool-a"))
        provider.node_templates["asg-pool-a"] = self._template(cpu="4")
        runtime.store.create(pending_pod("p0", cpu="2"))
        runtime.store.create(
            self._mp_with_ref("cached", {"group": "a"}, "pool-a")
        )
        runtime.manager.reconcile_all()
        first = len(calls)
        assert first >= 1
        clock.advance(6)
        runtime.manager.reconcile_all()  # within TTL: no new provider call
        assert len(calls) == first

    def test_missing_ref_or_template_stays_empty(self, env):
        runtime, provider, clock = env
        # ref to a nonexistent SNG: row solves as nothing-fits, no error
        runtime.store.create(
            self._mp_with_ref("dangling", {"group": "x"}, "nope")
        )
        # no ref at all: the pre-existing empty-group behavior
        runtime.store.create(pending_mp("plain", {"group": "y"}))
        runtime.store.create(pending_pod("p0", cpu="1"))
        runtime.manager.reconcile_all()
        for name in ("dangling", "plain"):
            mp = runtime.store.get("MetricsProducer", "default", name)
            assert mp.status.pending_capacity.additional_nodes_needed == 0
            assert mp.status.pending_capacity.unschedulable_pods == 1

    def test_template_change_invalidates_encode_memo(self, env):
        runtime, provider, clock = env
        # resolutions are TTL-cached (no cloud API call on idle ticks);
        # zero the TTL so this test observes the change immediately
        runtime.producer_factory.template_cache_ttl = 0.0
        runtime.store.create(self._sng("pool-a"))
        provider.node_templates["asg-pool-a"] = self._template(cpu="4")
        for i in range(4):
            runtime.store.create(pending_pod(f"p{i}", cpu="2"))
        runtime.store.create(
            self._mp_with_ref("memo", {"group": "a"}, "pool-a")
        )
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "memo")
        assert mp.status.pending_capacity.additional_nodes_needed == 2
        # template doubles -> fingerprint must change -> fresh solve
        provider.node_templates["asg-pool-a"] = self._template(cpu="8")
        clock.advance(6)  # past the 5 s producer interval
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "memo")
        assert mp.status.pending_capacity.additional_nodes_needed == 1


class TestPendingCapacityDrivesAutoscaling:
    def test_full_loop_scale_up(self, env):
        """pending pods -> solver -> gauge -> HA (Value target) -> SNG."""
        runtime, provider, clock = env
        selector = {"group": "a"}
        provider.node_replicas["group-a"] = 1
        runtime.store.create(ready_node("n1", selector, cpu="4", memory="8Gi"))
        for i in range(6):
            runtime.store.create(pending_pod(f"p{i}", cpu="2"))
        runtime.store.create(pending_mp("group-a", selector))
        runtime.store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="group-a"),
                spec=ScalableNodeGroupSpec(
                    replicas=1, type="FakeNodeGroup", id="group-a"
                ),
            )
        )
        # current + additional nodes, expressed with an AverageValue target
        # of 1 on the additional-nodes signal plus min bound at current size
        runtime.store.create(
            HorizontalAutoscaler(
                metadata=ObjectMeta(name="group-a"),
                spec=HorizontalAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="ScalableNodeGroup", name="group-a"
                    ),
                    min_replicas=1,
                    max_replicas=100,
                    metrics=[
                        Metric(
                            prometheus=PrometheusMetricSource(
                                query='karpenter_pending_capacity_additional_nodes_needed{name="group-a"}',
                                target=MetricTarget(type="AverageValue", value=1),
                            )
                        )
                    ],
                ),
            )
        )

        runtime.manager.reconcile_all()
        runtime.manager.reconcile_all()
        # 6 pods x 2cpu on 4cpu nodes -> 3 additional nodes -> desired 3
        ha = runtime.store.get("HorizontalAutoscaler", "default", "group-a")
        assert ha.status.desired_replicas == 3
        assert provider.node_replicas["group-a"] == 3


class TestConservativeGroupShape:
    def test_heterogeneous_group_uses_min_shape(self, env):
        """A pod that only fits the elementwise-MAX phantom of two real node
        shapes must NOT be reported schedulable (max would loop scale-ups
        forever without ever placing the pod)."""
        runtime, provider, clock = env
        selector = {"group": "het"}
        runtime.store.create(
            ready_node("big-cpu", selector, cpu="4", memory="2Gi")
        )
        runtime.store.create(
            ready_node("big-mem", selector, cpu="2", memory="8Gi")
        )
        # needs cpu=4 AND mem=8Gi: no real node shape can host it
        runtime.store.create(pending_pod("phantom", cpu="4", memory="8Gi"))
        # fits the min shape (cpu<=2, mem<=2Gi): genuinely schedulable
        runtime.store.create(pending_pod("real", cpu="1", memory="1Gi"))
        runtime.store.create(pending_mp("het", selector))

        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "het")
        assert mp.status.pending_capacity.pending_pods == 1
        assert mp.status.pending_capacity.unschedulable_pods == 1
        assert mp.status.pending_capacity.additional_nodes_needed == 1


class TestExtendedResources:
    def test_extended_resource_is_a_constraint(self, env):
        """A pod requesting an extended resource (tpu chips) must not be
        packed onto a group whose nodes lack it — and must be packed onto a
        group that has it."""
        runtime, provider, clock = env
        cpu_only = ready_node("cpu-node", {"group": "cpu"})
        tpu_node = ready_node("tpu-node", {"group": "tpu"})
        tpu_node.status.allocatable["google.com/tpu"] = (
            cpu_only.status.allocatable["cpu"].__class__.parse("4")
        )
        runtime.store.create(cpu_only)
        runtime.store.create(tpu_node)

        accel = pending_pod("accel", cpu="1", memory="1Gi")
        accel.spec.containers[0].requests["google.com/tpu"] = (
            cpu_only.status.allocatable["cpu"].__class__.parse("2")
        )
        runtime.store.create(accel)
        runtime.store.create(pending_mp("cpu-group", {"group": "cpu"}))
        runtime.store.create(pending_mp("tpu-group", {"group": "tpu"}))

        runtime.manager.reconcile_all()
        cpu_mp = runtime.store.get("MetricsProducer", "default", "cpu-group")
        tpu_mp = runtime.store.get("MetricsProducer", "default", "tpu-group")
        assert cpu_mp.status.pending_capacity.pending_pods == 0
        assert tpu_mp.status.pending_capacity.pending_pods == 1
        assert tpu_mp.status.pending_capacity.additional_nodes_needed == 1
        assert tpu_mp.status.pending_capacity.unschedulable_pods == 0

    def test_unprovided_extended_resource_is_unschedulable(self, env):
        runtime, provider, clock = env
        runtime.store.create(ready_node("n", {"group": "cpu"}))
        gpu = pending_pod("gpu", cpu="1", memory="1Gi")
        gpu.spec.containers[0].requests["nvidia.com/gpu"] = (
            gpu.spec.containers[0].requests["cpu"].__class__.parse("1")
        )
        runtime.store.create(gpu)
        runtime.store.create(pending_mp("cpu-group", {"group": "cpu"}))

        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "cpu-group")
        assert mp.status.pending_capacity.pending_pods == 0
        assert mp.status.pending_capacity.unschedulable_pods == 1


ZONE_KEY = "topology.kubernetes.io/zone"


def spread_pod(name, keys=(ZONE_KEY,), max_skew=1,
               when="DoNotSchedule", cpu="1", affinity=None):
    from karpenter_tpu.api.core import TopologySpreadConstraint

    pod = pending_pod(name, cpu=cpu, memory="1Gi")
    pod.spec.affinity = affinity
    pod.spec.topology_spread_constraints = [
        TopologySpreadConstraint(
            max_skew=max_skew, topology_key=key, when_unsatisfiable=when
        )
        for key in keys
    ]
    return pod


class TestTopologySpread:
    """Hard topologySpreadConstraints through the full signal: balanced
    per-domain weight splitting (producers/pendingcapacity
    _expand_spread_rows). The reference stubs the whole producer; the
    design intent anchor is DESIGN.md 'Pending Pods'."""

    def _zoned(self, runtime, zones=("a", "b", "c")):
        for z in zones:
            runtime.store.create(
                ready_node(
                    f"n-{z}", {"group": z, ZONE_KEY: f"us-{z}"},
                    cpu="64", pods="110",
                )
            )
            runtime.store.create(pending_mp(f"group-{z}", {"group": z}))

    def _pods_per_group(self, runtime, names):
        return {
            n: runtime.store.get("MetricsProducer", "default", n)
            .status.pending_capacity.pending_pods
            for n in names
        }

    def test_zone_spread_balances_across_groups(self, env):
        runtime, provider, clock = env
        self._zoned(runtime)
        for i in range(10):
            runtime.store.create(spread_pod(f"p{i}"))
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        )
        # balanced chunks: 10 = 4 + 3 + 3, never all in one zone
        assert sorted(counts.values(), reverse=True) == [4, 3, 3]

    def test_unconstrained_pods_still_pile_first_feasible(self, env):
        """Control: without the constraint the solver routes every pod to
        its first feasible group — proves the balance above is the
        constraint's doing."""
        runtime, provider, clock = env
        self._zoned(runtime)
        for i in range(10):
            runtime.store.create(pending_pod(f"p{i}", memory="1Gi"))
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        )
        assert sorted(counts.values(), reverse=True) == [10, 0, 0]

    def test_groups_missing_key_are_excluded(self, env):
        """kube-scheduler's PodTopologySpread filter: a node (here: group)
        without the topology key cannot satisfy DoNotSchedule."""
        runtime, provider, clock = env
        runtime.store.create(
            ready_node("n-z", {"group": "z", ZONE_KEY: "us-z"}, cpu="64")
        )
        runtime.store.create(ready_node("n-bare", {"group": "bare"}, cpu="64"))
        runtime.store.create(pending_mp("group-z", {"group": "z"}))
        runtime.store.create(pending_mp("group-bare", {"group": "bare"}))
        for i in range(4):
            runtime.store.create(spread_pod(f"p{i}"))
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(runtime, ["group-z", "group-bare"])
        assert counts == {"group-z": 4, "group-bare": 0}

    def test_no_domain_anywhere_is_unschedulable(self, env):
        runtime, provider, clock = env
        runtime.store.create(ready_node("n", {"group": "bare"}, cpu="64"))
        runtime.store.create(pending_mp("group-bare", {"group": "bare"}))
        for i in range(3):
            runtime.store.create(spread_pod(f"p{i}"))
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "group-bare")
        assert mp.status.pending_capacity.pending_pods == 0
        assert mp.status.pending_capacity.unschedulable_pods == 3

    def test_hostname_spread_is_satisfied_by_balance(self, env):
        """Domains are the nodes a scale-up adds; balanced placement
        satisfies any maxSkew >= 1, so hostname constraints neither split
        nor exclude (api/core.spread_shape drops them)."""
        runtime, provider, clock = env
        self._zoned(runtime, zones=("a", "b"))
        for i in range(6):
            runtime.store.create(
                spread_pod(f"p{i}", keys=("kubernetes.io/hostname",))
            )
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values(), reverse=True) == [6, 0]

    def test_schedule_anyway_is_soft(self, env):
        runtime, provider, clock = env
        self._zoned(runtime, zones=("a", "b"))
        for i in range(6):
            runtime.store.create(spread_pod(f"p{i}", when="ScheduleAnyway"))
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values(), reverse=True) == [6, 0]

    def test_spread_chunk_in_affinity_forbidden_zone_is_unschedulable(
        self, env
    ):
        """Documented conservative composition: domains are computed from
        topology labels alone, so the chunk split into a zone the pod's
        REQUIRED affinity rules out reports unschedulable rather than
        silently re-packing into the allowed zone."""
        from karpenter_tpu.api.core import (
            Affinity,
            NodeAffinity,
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
        )

        runtime, provider, clock = env
        self._zoned(runtime, zones=("a", "b"))
        affinity = Affinity(
            node_affinity=NodeAffinity(
                required_during_scheduling_ignored_during_execution=NodeSelector(
                    node_selector_terms=[
                        NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(
                                    key=ZONE_KEY,
                                    operator="In",
                                    values=["us-a"],
                                )
                            ]
                        )
                    ]
                )
            )
        )
        for i in range(6):
            runtime.store.create(spread_pod(f"p{i}", affinity=affinity))
        runtime.manager.reconcile_all()
        a = runtime.store.get("MetricsProducer", "default", "group-a")
        b = runtime.store.get("MetricsProducer", "default", "group-b")
        assert a.status.pending_capacity.pending_pods == 3
        assert b.status.pending_capacity.pending_pods == 0
        assert a.status.pending_capacity.unschedulable_pods == 3

    def test_distinct_spread_shapes_do_not_merge_in_dedup(self, env):
        """Identical pods except for the constraint must dedup into
        separate rows: one set spreads, the other piles."""
        runtime, provider, clock = env
        self._zoned(runtime, zones=("a", "b"))
        for i in range(4):
            runtime.store.create(spread_pod(f"s{i}"))
        for i in range(4):
            runtime.store.create(pending_pod(f"u{i}", memory="1Gi"))
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(runtime, ["group-a", "group-b"])
        # 4 unconstrained pile on one group; 4 spread pods go 2+2
        assert sum(counts.values()) == 8
        assert min(counts.values()) == 2

    def test_multi_zone_group_is_not_a_domain(self, env):
        """A group spanning zones loses the zone key in its label
        INTERSECTION, so it cannot be attributed to a domain — spread
        pods avoid it rather than risk a skew the solver can't see."""
        runtime, provider, clock = env
        runtime.store.create(
            ready_node("m1", {"group": "multi", ZONE_KEY: "us-a"}, cpu="64")
        )
        runtime.store.create(
            ready_node("m2", {"group": "multi", ZONE_KEY: "us-b"}, cpu="64")
        )
        runtime.store.create(
            ready_node("z1", {"group": "z", ZONE_KEY: "us-c"}, cpu="64")
        )
        runtime.store.create(pending_mp("group-multi", {"group": "multi"}))
        runtime.store.create(pending_mp("group-z", {"group": "z"}))
        for i in range(4):
            runtime.store.create(spread_pod(f"p{i}"))
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(runtime, ["group-multi", "group-z"])
        assert counts == {"group-multi": 0, "group-z": 4}

    def test_all_encode_paths_agree_with_spread(self):
        """Oracle (store.list), pod-cache, and feed paths must emit the
        same statuses for spread-constrained fleets (the same invariant
        tests/test_columnar.py holds for the unconstrained encode)."""
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
            solve_pending,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.columnar import PendingFeed, PendingPodCache
        from karpenter_tpu.store.store import Store

        store = Store()
        cache = PendingPodCache(store)
        feed = PendingFeed(store, group_profile)
        for z in ("a", "b"):
            store.create(
                ready_node(f"n-{z}", {"group": z, ZONE_KEY: f"us-{z}"},
                           cpu="64")
            )
            store.create(pending_mp(f"group-{z}", {"group": z}))
        for i in range(5):
            store.create(spread_pod(f"p{i}"))

        results = []
        for kwargs in ({}, {"pod_cache": cache}, {"feed": feed}):
            mps = [
                mp for mp in store.list("MetricsProducer")
                if mp.spec.pending_capacity is not None
            ]
            solve_pending(store, mps, GaugeRegistry(), **kwargs)
            results.append(
                {
                    mp.metadata.name: (
                        mp.status.pending_capacity.pending_pods,
                        mp.status.pending_capacity.additional_nodes_needed,
                        mp.status.pending_capacity.unschedulable_pods,
                    )
                    for mp in mps
                }
            )
        assert results[0] == results[1] == results[2]
        assert results[0]["group-a"][0] == 3  # 5 = 3 + 2, balanced
        assert results[0]["group-b"][0] == 2

    def test_min_domains_caps_per_domain_at_max_skew(self, env):
        """minDomains > eligible domains: the scheduler treats the global
        minimum as 0, so each domain holds at most maxSkew pods and the
        excess is unschedulable (core/v1 minDomains semantics). The
        selector matches the pods' own labels — the realistic workload
        shape; only then do placed replicas accumulate into the skew
        (selfMatchNum), which is what the cap binds through."""
        from karpenter_tpu.api.core import TopologySpreadConstraint

        runtime, provider, clock = env
        self._zoned(runtime, zones=("a", "b"))
        for i in range(10):
            pod = pending_pod(f"p{i}", memory="1Gi")
            pod.metadata.labels = {"app": "web"}
            pod.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=2,
                    topology_key=ZONE_KEY,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector={"matchLabels": {"app": "web"}},
                    min_domains=3,
                )
            ]
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(runtime, ["group-a", "group-b"])
        # 2 domains < minDomains=3: maxSkew=2 pods per domain, 6 stuck
        assert counts == {"group-a": 2, "group-b": 2}
        total_unschedulable = sum(
            runtime.store.get("MetricsProducer", "default", g)
            .status.pending_capacity.unschedulable_pods
            for g in ("group-a", "group-b")
        )
        # unschedulable is a global count reported on every row's status
        assert total_unschedulable >= 6

    def test_min_domains_satisfied_is_plain_balance(self, env):
        from karpenter_tpu.api.core import TopologySpreadConstraint

        runtime, provider, clock = env
        self._zoned(runtime, zones=("a", "b", "c"))
        for i in range(9):
            pod = pending_pod(f"p{i}", memory="1Gi")
            pod.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=ZONE_KEY,
                    when_unsatisfiable="DoNotSchedule",
                    min_domains=3,
                )
            ]
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        )
        assert sorted(counts.values()) == [3, 3, 3]

    def test_paths_agree_after_shape_renumbering(self):
        """Regression: the remainder-rotation offset must key on row
        CONTENT, not dedup position. A long-lived cache numbers a churned
        toleration shape differently from a fresh oracle build, shifting
        byte-sorted row order — the split must not move with it."""
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            solve_pending,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.columnar import PendingPodCache
        from karpenter_tpu.store.store import Store

        store = Store()
        cache = PendingPodCache(store)  # watches from the start
        for z in ("a", "b"):
            store.create(
                ready_node(f"n-{z}", {"group": z, ZONE_KEY: f"us-{z}"},
                           cpu="64")
            )
            store.create(pending_mp(f"group-{z}", {"group": z}))
        churner = pending_pod(
            "u", memory="1Gi",
            tolerations=[Toleration(key="x", operator="Exists")],
        )
        churner = store.create(churner)
        for i in range(3):
            store.create(spread_pod(f"s{i}"))
        # re-tolerate: the cache registers shape Z AFTER the spread rows'
        # shape, a fresh oracle encoder numbers it BEFORE them
        churner.spec.tolerations = [Toleration(key="z", operator="Exists")]
        store.update(churner)

        results = []
        for kwargs in ({}, {"pod_cache": cache}):
            mps = [
                mp for mp in store.list("MetricsProducer")
                if mp.spec.pending_capacity is not None
            ]
            solve_pending(store, mps, GaugeRegistry(), **kwargs)
            results.append(
                {
                    mp.metadata.name:
                    mp.status.pending_capacity.pending_pods
                    for mp in mps
                }
            )
        assert results[0] == results[1]


def anti_pod(name, keys=("kubernetes.io/hostname",), labels=None,
             cpu="1", self_match=True, co_keys=(), selector_labels=None):
    """A pod with required podAntiAffinity (and optionally podAffinity)
    whose selector matches its own labels (self_match) or a foreign app.
    selector_labels narrows the selector to a subset of the labels (the
    StatefulSet shape: shared selector, per-pod extra labels)."""
    from karpenter_tpu.api.core import (
        Affinity,
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
        PodAntiAffinity,
    )

    labels = dict(labels or {"app": "db"})
    pod = pending_pod(name, cpu=cpu, memory="1Gi")
    pod.metadata.labels = labels
    selector = LabelSelector(
        match_labels=(
            dict(selector_labels)
            if selector_labels is not None
            else dict(labels)
        )
        if self_match
        else {"app": "somebody-else"}
    )
    pod.spec.affinity = Affinity(
        pod_anti_affinity=PodAntiAffinity(
            required_during_scheduling_ignored_during_execution=[
                PodAffinityTerm(label_selector=selector, topology_key=key)
                for key in keys
            ]
        ),
        pod_affinity=(
            PodAffinity(
                required_during_scheduling_ignored_during_execution=[
                    PodAffinityTerm(
                        label_selector=LabelSelector(
                            match_labels=dict(selector_labels or labels)
                        ),
                        topology_key=key,
                    )
                    for key in co_keys
                ]
            )
            if co_keys
            else None
        ),
    )
    return pod


class TestSelfAntiAffinity:
    """Required inter-pod SELF-(anti-)affinity through the full signal:
    hostname anti-affinity takes one node per replica (the pod_exclusive
    solver operand), domain anti-affinity caps one replica per topology
    domain, co-location affinity pins the workload to one domain. The
    reference stubs the whole producer; the kube-scheduler's
    InterPodAffinity plugin defines the semantics being approximated."""

    def _zoned(self, runtime, zones=("a", "b", "c")):
        for z in zones:
            runtime.store.create(
                ready_node(
                    f"n-{z}", {"group": z, ZONE_KEY: f"us-{z}"},
                    cpu="64", pods="110",
                )
            )
            runtime.store.create(pending_mp(f"group-{z}", {"group": z}))

    def _pods_per_group(self, runtime, names):
        return {
            n: runtime.store.get("MetricsProducer", "default", n)
            .status.pending_capacity.pending_pods
            for n in names
        }

    def test_hostname_anti_takes_one_node_per_replica(self, env):
        """5 one-cpu replicas on 64-cpu nodes: an unconstrained workload
        packs into ONE node; one-replica-per-node demands FIVE."""
        runtime, provider, clock = env
        selector = {"group": "a"}
        runtime.store.create(ready_node("n1", selector, cpu="64", pods="110"))
        runtime.store.create(pending_mp("group-a", selector))
        for i in range(5):
            runtime.store.create(anti_pod(f"p{i}"))
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.pending_pods == 5
        assert mp.status.pending_capacity.additional_nodes_needed == 5
        assert mp.status.pending_capacity.unschedulable_pods == 0

    def test_unconstrained_control_packs_one_node(self, env):
        runtime, provider, clock = env
        selector = {"group": "a"}
        runtime.store.create(ready_node("n1", selector, cpu="64", pods="110"))
        runtime.store.create(pending_mp("group-a", selector))
        for i in range(5):
            runtime.store.create(pending_pod(f"p{i}", memory="1Gi"))
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.additional_nodes_needed == 1

    def test_zone_anti_caps_one_per_domain(self, env):
        """5 replicas, 3 zones: one per zone schedules, 2 are
        unschedulable by anti-affinity (every domain taken)."""
        runtime, provider, clock = env
        self._zoned(runtime)
        for i in range(5):
            runtime.store.create(anti_pod(f"p{i}", keys=(ZONE_KEY,)))
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        )
        assert sorted(counts.values()) == [1, 1, 1]
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.unschedulable_pods == 2

    def test_zone_anti_within_domain_count_all_schedule(self, env):
        runtime, provider, clock = env
        self._zoned(runtime)
        for i in range(3):
            runtime.store.create(anti_pod(f"p{i}", keys=(ZONE_KEY,)))
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        )
        assert sorted(counts.values()) == [1, 1, 1]
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.unschedulable_pods == 0

    def test_foreign_selector_is_not_modeled(self, env):
        """Anti-affinity against ANOTHER app's pods needs pairwise pod
        state (documented out of scope): the pods behave unconstrained."""
        runtime, provider, clock = env
        self._zoned(runtime)
        for i in range(6):
            runtime.store.create(
                anti_pod(f"p{i}", keys=(ZONE_KEY,), self_match=False)
            )
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        )
        assert sorted(counts.values(), reverse=True) == [6, 0, 0]

    def test_two_workloads_each_get_their_own_domains(self, env):
        """Different labels = different anti shapes: each workload caps
        1/zone independently, so 2 workloads x 3 replicas fill each zone
        with 2 pods."""
        runtime, provider, clock = env
        self._zoned(runtime)
        for i in range(3):
            runtime.store.create(
                anti_pod(f"db{i}", keys=(ZONE_KEY,), labels={"app": "db"})
            )
            runtime.store.create(
                anti_pod(
                    f"web{i}", keys=(ZONE_KEY,), labels={"app": "web"}
                )
            )
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        )
        assert sorted(counts.values()) == [2, 2, 2]
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.unschedulable_pods == 0

    def test_hostname_and_zone_anti_compose(self, env):
        """hostname + zone keys together: one per zone AND a whole node
        each — nodes_needed equals the scheduled replica count even
        though each zone's node could hold 64 of them."""
        runtime, provider, clock = env
        self._zoned(runtime)
        for i in range(3):
            runtime.store.create(
                anti_pod(f"p{i}", keys=("kubernetes.io/hostname", ZONE_KEY))
            )
        runtime.manager.reconcile_all()
        for g in ("group-a", "group-b", "group-c"):
            mp = runtime.store.get("MetricsProducer", "default", g)
            assert mp.status.pending_capacity.pending_pods == 1
            assert mp.status.pending_capacity.additional_nodes_needed == 1

    def test_co_location_pins_one_domain(self, env):
        """Required self pod-AFFINITY on the zone key: groups missing the
        key are excluded and the whole workload lands in ONE zone."""
        runtime, provider, clock = env
        self._zoned(runtime, zones=("a", "b"))
        runtime.store.create(ready_node("n-bare", {"group": "bare"}, cpu="64"))
        runtime.store.create(pending_mp("group-bare", {"group": "bare"}))
        for i in range(4):
            runtime.store.create(
                anti_pod(f"p{i}", keys=(), co_keys=(ZONE_KEY,))
            )
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-bare"]
        )
        assert counts["group-bare"] == 0
        assert sorted(counts.values(), reverse=True) == [4, 0, 0]

    def test_anti_governs_over_spread_split(self, env):
        """A row with BOTH hard spread and zone anti-affinity: the anti
        rule (1 per domain — the most balanced split possible) governs;
        pods beyond the domain count are unschedulable."""
        runtime, provider, clock = env
        self._zoned(runtime, zones=("a", "b"))
        for i in range(4):
            pod = anti_pod(f"p{i}", keys=(ZONE_KEY,))
            from karpenter_tpu.api.core import TopologySpreadConstraint

            pod.spec.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=ZONE_KEY,
                    when_unsatisfiable="DoNotSchedule",
                )
            ]
            runtime.store.create(pod)
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values()) == [1, 1]
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.unschedulable_pods == 2

    def test_distinct_anti_shapes_do_not_merge_in_dedup(self, env):
        """Identical pods except the constraint dedup into separate rows:
        the exclusive set takes a node each, the rest pack together."""
        runtime, provider, clock = env
        selector = {"group": "a"}
        runtime.store.create(ready_node("n1", selector, cpu="64", pods="110"))
        runtime.store.create(pending_mp("group-a", selector))
        for i in range(3):
            runtime.store.create(anti_pod(f"x{i}"))
        for i in range(3):
            runtime.store.create(pending_pod(f"u{i}", memory="1Gi"))
        runtime.manager.reconcile_all()
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.pending_pods == 6
        # 3 exclusive nodes + 1 shared node for the unconstrained trio
        assert mp.status.pending_capacity.additional_nodes_needed == 4

    def test_all_encode_paths_agree_with_anti(self):
        """Oracle (store.list), pod-cache, and feed paths emit the same
        statuses for anti-affinity fleets (the spread/columnar
        invariant, extended to the new constraint)."""
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            group_profile,
            solve_pending,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.columnar import PendingFeed, PendingPodCache
        from karpenter_tpu.store.store import Store

        store = Store()
        cache = PendingPodCache(store)
        feed = PendingFeed(store, group_profile)
        for z in ("a", "b"):
            store.create(
                ready_node(f"n-{z}", {"group": z, ZONE_KEY: f"us-{z}"},
                           cpu="64")
            )
            store.create(pending_mp(f"group-{z}", {"group": z}))
        for i in range(3):
            store.create(anti_pod(f"h{i}"))
            store.create(anti_pod(f"z{i}", keys=(ZONE_KEY,)))

        results = []
        for kwargs in ({}, {"pod_cache": cache}, {"feed": feed}):
            mps = [
                mp for mp in store.list("MetricsProducer")
                if mp.spec.pending_capacity is not None
            ]
            solve_pending(store, mps, GaugeRegistry(), **kwargs)
            results.append(
                {
                    mp.metadata.name: (
                        mp.status.pending_capacity.pending_pods,
                        mp.status.pending_capacity.additional_nodes_needed,
                        mp.status.pending_capacity.unschedulable_pods,
                    )
                    for mp in mps
                }
            )
        assert results[0] == results[1] == results[2]
        # 3 hostname pods -> 3 nodes in the first zone group; zone pods
        # 1 per zone, third replica unschedulable (2 domains)
        assert results[0]["group-a"][2] == 1

    def test_statefulset_per_pod_labels_share_one_anti_group(self, env):
        """StatefulSets stamp unique per-pod labels (pod-name/index) on
        replicas; workload identity keys on the SELECTOR, so the
        replicas still form one anti-group: 1 per zone, excess
        unschedulable (r3 code review finding)."""
        runtime, provider, clock = env
        self._zoned(runtime)
        for i in range(5):
            runtime.store.create(
                anti_pod(
                    f"db-{i}",
                    keys=(ZONE_KEY,),
                    labels={
                        "app": "db",
                        "statefulset.kubernetes.io/pod-name": f"db-{i}",
                    },
                    selector_labels={"app": "db"},
                )
            )
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        )
        assert sorted(counts.values()) == [1, 1, 1]
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.unschedulable_pods == 2

    def test_multi_key_anti_caps_every_key(self, env):
        """Anti-affinity on rack AND zone, 4 racks across 2 zones: only
        2 replicas can place (one per zone), even though 4 racks exist
        (r3 code review finding — a first-key-only split would claim 4)."""
        runtime, provider, clock = env
        rack = "example.com/rack"
        layout = [("r1", "z1"), ("r2", "z1"), ("r3", "z2"), ("r4", "z2")]
        for r, z in layout:
            runtime.store.create(
                ready_node(
                    f"n-{r}",
                    {"group": r, rack: r, ZONE_KEY: f"us-{z}"},
                    cpu="64", pods="110",
                )
            )
            runtime.store.create(pending_mp(f"group-{r}", {"group": r}))
        for i in range(4):
            runtime.store.create(
                anti_pod(f"p{i}", keys=(rack, ZONE_KEY))
            )
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, [f"group-{r}" for r, _ in layout]
        )
        assert sum(counts.values()) == 2  # one per ZONE, not per rack
        mp = runtime.store.get("MetricsProducer", "default", "group-r1")
        assert mp.status.pending_capacity.unschedulable_pods == 2
        # and the two placed replicas sit in different zones
        placed = [r for r, z in layout if counts[f"group-{r}"] == 1]
        zones = {dict(layout)[r] for r in placed}
        assert len(zones) == 2

    def test_zone_anti_with_region_co_location_stays_in_one_region(
        self, env
    ):
        """'Spread across zones within one region': zone anti + region
        co-location. Two zones in region r1, one zone in region r2 —
        all replicas must land in r1 (2 domains beat 1), the third
        replica unschedulable (r3 code review finding — independent
        per-zone assignment would claim all 3 across regions)."""
        runtime, provider, clock = env
        region = "topology.kubernetes.io/region"
        layout = [("a", "r1"), ("b", "r1"), ("c", "r2")]
        for z, r in layout:
            runtime.store.create(
                ready_node(
                    f"n-{z}",
                    {"group": z, ZONE_KEY: f"us-{z}", region: r},
                    cpu="64", pods="110",
                )
            )
            runtime.store.create(pending_mp(f"group-{z}", {"group": z}))
        for i in range(3):
            runtime.store.create(
                anti_pod(f"p{i}", keys=(ZONE_KEY,), co_keys=(region,))
            )
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(
            runtime, ["group-a", "group-b", "group-c"]
        )
        assert counts == {"group-a": 1, "group-b": 1, "group-c": 0}
        mp = runtime.store.get("MetricsProducer", "default", "group-a")
        assert mp.status.pending_capacity.unschedulable_pods == 1

    def test_co_only_multi_row_workload_pins_one_domain(self, env):
        """A co-location workload whose replicas differ in requests
        (mid-VPA) dedups into separate rows; the rows must still pin to
        ONE domain (r3 code review finding)."""
        runtime, provider, clock = env
        self._zoned(runtime, zones=("a", "b"))
        for i, cpu in enumerate(["1", "1", "2", "2"]):
            runtime.store.create(
                anti_pod(f"p{i}", keys=(), co_keys=(ZONE_KEY,), cpu=cpu)
            )
        runtime.manager.reconcile_all()
        counts = self._pods_per_group(runtime, ["group-a", "group-b"])
        assert sorted(counts.values(), reverse=True) == [4, 0]

    def test_anti_domain_handout_is_path_stable(self):
        """Regression (r3 code review): domain hand-out across a
        workload's request-identical rows must key on canonical row
        CONTENT, not dedup position. A long-lived cache numbers a
        churned toleration shape differently from a fresh oracle
        build, flipping byte-sorted row order; with a taint on one
        zone, a position-ordered hand-out would give the two paths
        different row->domain assignments and different outputs."""
        from karpenter_tpu.api.core import Taint, Toleration
        from karpenter_tpu.metrics.producers.pendingcapacity import (
            solve_pending,
        )
        from karpenter_tpu.metrics.registry import GaugeRegistry
        from karpenter_tpu.store.columnar import PendingPodCache
        from karpenter_tpu.store.store import Store

        store = Store()
        cache = PendingPodCache(store)  # watches from the start
        # zone-a tainted: only the tolerating row can use domain us-a
        store.create(
            ready_node(
                "n-a", {"group": "a", ZONE_KEY: "us-a"}, cpu="64",
                taints=[Taint(key="dedicated", value="db")],
            )
        )
        store.create(
            ready_node("n-b", {"group": "b", ZONE_KEY: "us-b"}, cpu="64")
        )
        for z in ("a", "b"):
            store.create(pending_mp(f"group-{z}", {"group": z}))
        # churner forces the cache to register a late toleration shape
        # (renumbering arena ids between cache and oracle builds)
        churner = pending_pod(
            "u", memory="1Gi",
            tolerations=[Toleration(key="x", operator="Exists")],
        )
        churner = store.create(churner)
        # ONE workload (same selector/labels), zone anti-affinity, two
        # request-identical rows differing only in tolerations
        tol = anti_pod("db-tol", keys=(ZONE_KEY,))
        tol.spec.tolerations = [
            Toleration(key="dedicated", operator="Equal", value="db",
                       effect="NoSchedule")
        ]
        store.create(tol)
        store.create(anti_pod("db-plain", keys=(ZONE_KEY,)))
        churner.spec.tolerations = [Toleration(key="z", operator="Exists")]
        store.update(churner)

        results = []
        for kwargs in ({}, {"pod_cache": cache}):
            mps = [
                mp for mp in store.list("MetricsProducer")
                if mp.spec.pending_capacity is not None
            ]
            solve_pending(store, mps, GaugeRegistry(), **kwargs)
            results.append(
                {
                    mp.metadata.name: (
                        mp.status.pending_capacity.pending_pods,
                        mp.status.pending_capacity.unschedulable_pods,
                    )
                    for mp in mps
                }
            )
        assert results[0] == results[1]
