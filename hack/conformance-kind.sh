#!/usr/bin/env bash
# Boot a GENUINE kube-apiserver (a kind cluster) and run the env-gated
# real-apiserver conformance tier against it — the role the reference's
# envtest plays (reference: pkg/test/environment/local.go:53-157 boots
# kube-apiserver + etcd for EVERY test run).
#
# Usage: hack/conformance-kind.sh [log-file]
# Requires: kind, kubectl, a container engine. CI provides all three
# (.github/workflows/presubmit.yaml `conformance` job); on hosts without
# them the script exits 3 after logging exactly what was missing, so the
# attempt itself is recordable evidence.
set -uo pipefail
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/conformance-kind.log}"
CLUSTER="${CLUSTER:-karpenter-conformance}"
: > "$LOG"
. hack/lib-kind.sh

require_kind_tools "the real-apiserver conformance tier"
boot_kind_cluster "$CLUSTER"

# KubeClient authenticates with a bearer token + CA bundle (the in-cluster
# pattern); mint both from a cluster-admin serviceaccount
kubectl create serviceaccount karpenter-conf >>"$LOG" 2>&1
kubectl create clusterrolebinding karpenter-conf-admin \
  --clusterrole=cluster-admin \
  --serviceaccount=default:karpenter-conf >>"$LOG" 2>&1
TOKEN=$(kubectl create token karpenter-conf --duration=2h)
SERVER=$(kubectl config view --minify -o \
  jsonpath='{.clusters[0].cluster.server}')
CADIR=$(mktemp -d)
kubectl config view --raw --minify -o \
  jsonpath='{.clusters[0].cluster.certificate-authority-data}' \
  | base64 -d > "$CADIR/ca.crt"

log "running the conformance tier against $SERVER"
if KARPENTER_TEST_REAL_APISERVER="$SERVER" \
   KARPENTER_TEST_REAL_APISERVER_TOKEN="$TOKEN" \
   KARPENTER_TEST_REAL_APISERVER_CA="$CADIR/ca.crt" \
   python -m pytest tests/test_real_apiserver.py -v -rs 2>&1 | tee -a "$LOG"; then
  log "conformance tier PASSED against a real apiserver"
else
  fail "conformance tier FAILED (see $LOG)"
fi
