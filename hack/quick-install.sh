#!/usr/bin/env bash
# Install cert-manager, the Prometheus stack, and karpenter-tpu into the
# current kubecontext (reference: hack/quick-install.sh:40-66).
set -euo pipefail

main() {
  cert_manager
  prometheus
  karpenter
  echo "karpenter-tpu installed."
}

cert_manager() {
  kubectl apply -f https://github.com/cert-manager/cert-manager/releases/latest/download/cert-manager.yaml
  kubectl wait --for=condition=Available --timeout=120s \
    -n cert-manager deployment/cert-manager-webhook
}

prometheus() {
  helm repo add prometheus-community https://prometheus-community.github.io/helm-charts --force-update
  helm upgrade --install prometheus prometheus-community/kube-prometheus-stack \
    --namespace monitoring --create-namespace \
    --set grafana.enabled=false
}

karpenter() {
  # Build the image the Deployment references (config/manager/
  # manager.yaml pins karpenter-tpu:latest) and apply config/ with it;
  # `make apply` also handles a custom IMAGE_REPO/IMAGE_TAG. On kind,
  # run `make kind-load` first so the node can pull the local image.
  make -C "$(dirname "$0")/.." apply
  kubectl wait --for=condition=Available --timeout=120s \
    -n karpenter deployment/karpenter-tpu
}

usage() {
  cat <<USAGE
Usage: $0 [--delete]
Installs cert-manager + kube-prometheus-stack + karpenter-tpu.
USAGE
}

if [[ "${1:-}" == "--delete" ]]; then
  kubectl delete -k config/ --ignore-not-found
  exit 0
elif [[ "${1:-}" == "-h" || "${1:-}" == "--help" ]]; then
  usage
  exit 0
fi

main
