#!/usr/bin/env bash
# Watch for the flaky axon TPU tunnel to come up and, the moment it does,
# capture the full bench suite (hack/tpu-bench-all.sh) before it can drop
# again. Designed to run in the background for hours: probes with a hard
# timeout, logs every attempt, and exits after one successful capture.
#
# Usage: hack/tpu-watch-capture.sh [out-jsonl] [probe-interval-seconds]
set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu-bench-capture.jsonl}"
INTERVAL="${2:-180}"
PROBE_TIMEOUT="${PROBE_TIMEOUT:-120}"
# Hard ceiling on one capture run: if the tunnel drops between the probe and
# an in-process jit, bench.py can hang with no subprocess timeout to save it.
CAPTURE_TIMEOUT="${CAPTURE_TIMEOUT:-5400}"

attempt=0
while :; do
  attempt=$((attempt + 1))
  echo "[$(date -u +%H:%M:%S)] probe #$attempt" >&2
  if timeout "$PROBE_TIMEOUT" python -c \
      "import jax; assert jax.default_backend() not in ('cpu',); print(jax.devices())" \
      >&2 2>&1; then
    echo "[$(date -u +%H:%M:%S)] TPU up after $attempt probe(s); capturing" >&2
    if timeout "$CAPTURE_TIMEOUT" hack/tpu-bench-all.sh > "$OUT" 2>/tmp/tpu-bench-capture.err; then
      # a capture that fell back to CPU mid-suite is NOT evidence — the
      # whole point is a real-chip record; reject and keep watching
      if grep -q '"error"\|(cpu)\|cpu fallback' "$OUT"; then
        # never leave polluted data at the advertised evidence path
        mv -f "$OUT" "$OUT.rejected"
        echo "[$(date -u +%H:%M:%S)] capture has CPU-fallback/error rows (kept at $OUT.rejected); retrying" >&2
      else
        echo "[$(date -u +%H:%M:%S)] capture complete: $OUT" >&2
        # While the tunnel is still up, also pin the real-chip Pallas
        # equality artifact (compiled Mosaic == XLA on hardware) — the
        # claim otherwise rests on prose (r3 verdict, weak #5).
        if timeout 1800 env KARPENTER_TEST_REAL_BACKEND=1 \
          python -m pytest tests/test_pallas_binpack.py -v -rs \
          > "${OUT%.jsonl}-pallas-equality.log" 2>&1; then
          echo "[$(date -u +%H:%M:%S)] pallas equality log: ${OUT%.jsonl}-pallas-equality.log" >&2
        else
          echo "[$(date -u +%H:%M:%S)] pallas equality FAILED (see ${OUT%.jsonl}-pallas-equality.log)" >&2
        fi
        exit 0
      fi
    else
      mv -f "$OUT" "$OUT.rejected" 2>/dev/null
      echo "[$(date -u +%H:%M:%S)] capture FAILED (tunnel dropped mid-run?); retrying" >&2
    fi
  fi
  sleep "$INTERVAL"
done
