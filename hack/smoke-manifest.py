#!/usr/bin/env python3
"""Transform `kubectl kustomize config/` output for a bare kind cluster
(hack/kind-smoke.sh). The stock tree targets production GKE: cert-manager
certificates, a ServiceMonitor, failurePolicy=Fail admission webhooks, a
TPU-requesting solver container, and a GKE node pin. None of those exist
on kind, and each would wedge the smoke in a different way (unknown CRD
kinds at apply time; every CR create rejected by an unreachable webhook;
the pod Pending forever on google.com/tpu). The smoke keeps everything
else exactly as shipped: image, RBAC, probes, the two-container split.

Usage: kubectl kustomize config/ | python3 hack/smoke-manifest.py [image]

Drops: cert-manager.io and monitoring.coreos.com documents,
Validating/MutatingWebhookConfigurations.
Rewrites the controller Deployment: replicas 1, no nodeSelector, fake
cloud provider + no webhook listener, no TPU resource claims on the
solver, no cert-manager secret volume, and (optionally) the image tag.
"""

import sys

import yaml

SMOKE_ARGS = [
    "--apiserver=https://kubernetes.default.svc",
    "--cloud-provider=fake",
    "--solver-uri=127.0.0.1:9090",
]


def dropped(doc) -> bool:
    api = doc.get("apiVersion", "")
    if api.startswith(("cert-manager.io/", "monitoring.coreos.com/")):
        return True
    return doc.get("kind", "").endswith("WebhookConfiguration")


def _drop_cert_entries(holder, key) -> None:
    """Remove only the cert-manager entries (name == 'cert') from a
    volumes/volumeMounts list: any other entry added later is part of
    the shipped spec and the smoke must keep validating it."""
    kept = [e for e in holder.get(key, []) if e.get("name") != "cert"]
    if kept:
        holder[key] = kept
    else:
        holder.pop(key, None)


def _drop_tpu_claims(container) -> None:
    resources = container.get("resources", {})
    for section in ("requests", "limits"):
        entries = resources.get(section)
        if entries:
            entries.pop("google.com/tpu", None)
            # an empty limits/requests map is valid but noisy
            if not entries:
                resources.pop(section)


def rewrite_deployment(doc, image) -> None:
    spec = doc["spec"]
    spec["replicas"] = 1
    pod = spec["template"]["spec"]
    pod.pop("nodeSelector", None)
    _drop_cert_entries(pod, "volumes")
    for container in pod.get("containers", []):
        if image:
            container["image"] = image
        _drop_cert_entries(container, "volumeMounts")
        _drop_tpu_claims(container)
        if container.get("name") == "controller":
            container["args"] = list(SMOKE_ARGS)


def transform(docs, image):
    """The whole smoke pipeline over parsed documents — main() and the
    pinning test (tests/test_smoke_manifest.py) both call THIS, so a
    new transform step can never be tested-around."""
    kept = []
    for doc in docs:
        if dropped(doc):
            continue
        if doc.get("kind") == "Deployment":
            rewrite_deployment(doc, image)
        kept.append(doc)
    return kept


def main() -> int:
    image = sys.argv[1] if len(sys.argv) > 1 else ""
    docs = [d for d in yaml.safe_load_all(sys.stdin) if d is not None]
    yaml.safe_dump_all(transform(docs, image), sys.stdout, sort_keys=False)
    return 0


if __name__ == "__main__":
    sys.exit(main())
