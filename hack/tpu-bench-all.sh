#!/usr/bin/env bash
# Capture the full TPU measurement suite in one run, as input for
# updating docs/BENCHMARKS.md: solve on both backends, honest e2e, fleet
# decisions, multi-cluster re-pack, and the 1M-pod configuration. Each
# line is one JSON record on stdout; everything else goes to stderr.
# Exits nonzero if ANY configuration failed.
set -uo pipefail
cd "$(dirname "$0")/.."
failures=0
# The suite is run right after a successful probe (hack/tpu-watch-capture.sh
# or an operator who just checked the chip), so a mid-suite hang means the
# tunnel dropped — fall back fast rather than letting every config in the
# list below wait out the default 21-minute hang schedule independently
# (hours of nothing).
HANG_SCHEDULE="${PROBE_HANG_SCHEDULE:-}"
for args in \
    "--backend pallas" \
    "--backend xla" \
    "--affinity 0.5 --iters 10" \
    "--anti 0.3 --iters 10" \
    "--e2e" \
    "--e2e --affinity 0.3" \
    "--e2e --anti 0.05" \
    "--e2e --spread 0.1" \
    "--e2e --pods 1000000 --churn 1000 --iters 5" \
    "--decide 100000" \
    "--clusters 10 --types 30 --pods 100000" \
    "--pods 1000000 --iters 5" \
    "--multitenant --tenants 1000 --tenant-rows 4 --iters 10" \
    ; do
  echo "=== bench.py $args ===" >&2
  # shellcheck disable=SC2086
  python bench.py $args --probe-hang-schedule "$HANG_SCHEDULE" || {
    echo "{\"error\": \"bench.py $args failed\"}"
    failures=$((failures + 1))
  }
done
exit "$((failures > 0))"
