#!/usr/bin/env python
"""AST lint gate for `make verify`.

reference: Makefile:25-38 — the reference's verify runs go vet +
golangci-lint and its battletest gates on gocyclo <= 10. This image ships
no ruff/pyflakes and installs are forbidden, so the same spirit is
enforced with the stdlib ast module:

  * cyclomatic complexity bound per function (branches + bool ops),
  * unused imports (module scope and function scope),
  * duplicated keys in dict literals,
  * mutable default arguments.

Scope is deliberately small and high-signal: every rule here is either
the reference's own gate (complexity) or a defect class that has no
legitimate instance in this codebase. Exceptions are declared inline
with `# lint: allow-complexity` on the def line for solver kernels whose
branch count is shape-unrolled math, not control-flow soup.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_COMPLEXITY = 10  # the reference's gocyclo gate (Makefile:25-31)

CHECK_ROOTS = (
    "karpenter_tpu",
    "tests",
    "hack",  # the gate checks itself
    "bench.py",
    "__graft_entry__.py",
)


def iter_files(root: Path):
    for entry in CHECK_ROOTS:
        path = root / entry
        if path.is_file():
            yield path
        else:
            yield from sorted(path.rglob("*.py"))


def _own_nodes(fn: ast.AST):
    """Walk a function's body WITHOUT descending into nested defs: each
    def is scored standalone (billing a closure's branches to its parent
    would double-count and force waivers on functions whose own control
    flow is simple)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(node))


def complexity(fn: ast.AST) -> int:
    """gocyclo-style: 1 + one per branch point (gocyclo counts if/for/
    case/&&/||; with/assert are not branches and are not counted)."""
    count = 1
    for node in _own_nodes(fn):
        if isinstance(
            node,
            (
                ast.If,
                ast.For,
                ast.AsyncFor,
                ast.While,
                ast.ExceptHandler,
                ast.IfExp,
            ),
        ):
            count += 1
        elif isinstance(node, ast.BoolOp):
            count += len(node.values) - 1
        elif isinstance(node, (ast.comprehension,)):
            count += 1 + len(node.ifs)
        elif isinstance(node, ast.Match):
            count += len(node.cases)
    return count


def _allowed(fn: ast.AST, source_lines) -> bool:
    line = source_lines[fn.lineno - 1]
    return "lint: allow-complexity" in line


class ImportTracker(ast.NodeVisitor):
    """Unused imports per scope (module + each function).

    Exemptions, matching pyflakes/ruff conventions: `from __future__`
    (a directive, not a binding), any import line carrying a `noqa`
    comment (the codebase's marker for side-effect imports that
    register providers/algorithms), and __init__.py files entirely
    (re-exports ARE the public API surface there).
    """

    def __init__(self, source_lines):
        self.problems = []
        self._lines = source_lines
        self._scopes = [{}]  # name -> (lineno, display)

    def _bind(self, name: str, lineno: int, display: str):
        if "noqa" in self._lines[lineno - 1]:
            return
        root = name.split(".")[0]
        self._scopes[-1][root] = (lineno, display)

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            self._bind(alias.asname or alias.name, node.lineno, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module == "__future__":
            return
        for alias in node.names:
            if alias.name == "*":
                continue
            self._bind(alias.asname or alias.name, node.lineno, alias.name)

    def _walk_scope(self, node):
        self._scopes.append({})
        self.generic_visit(node)
        scope = self._scopes.pop()
        body_names = _used_names(node)
        for name, (lineno, display) in scope.items():
            if name not in body_names:
                self.problems.append((lineno, f"unused import: {display}"))

    visit_FunctionDef = _walk_scope
    visit_AsyncFunctionDef = _walk_scope

    def finish(self, tree: ast.Module):
        used = _used_names(tree)
        for name, (lineno, display) in self._scopes[0].items():
            if name not in used:
                self.problems.append((lineno, f"unused import: {display}"))


def _names_in_string(text: str, used: set) -> None:
    """Quoted forward references ("Optional[int]") hide names in
    strings; parse plausible ones so valid code never fails the gate
    (__all__ strings get counted too — acceptable under-reporting,
    never a false positive)."""
    text = text.strip()
    if not text or len(text) >= 200 or "\n" in text:
        return
    try:
        for sub in ast.walk(ast.parse(text, mode="eval")):
            if isinstance(sub, ast.Name):
                used.add(sub.id)
    except (SyntaxError, ValueError):
        pass


def _used_names(tree) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # "a.b.c" marks a used
            inner = node
            while isinstance(inner, ast.Attribute):
                inner = inner.value
            if isinstance(inner, ast.Name):
                used.add(inner.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            _names_in_string(node.value, used)
    return used


def _check_function(node, lines, is_test: bool, problems: list) -> None:
    score = complexity(node)
    # tests are exempt from the complexity bound (the reference gates
    # gocyclo over pkg/, not its test trees); every other rule still
    # applies to them
    if score > MAX_COMPLEXITY and not is_test and not _allowed(node, lines):
        problems.append(
            (
                node.lineno,
                f"{node.name} complexity {score} > "
                f"{MAX_COMPLEXITY} (split it, or annotate "
                "`# lint: allow-complexity` with a reason)",
            )
        )
    for default in node.args.defaults + node.args.kw_defaults:
        if isinstance(default, (ast.List, ast.Dict, ast.Set)):
            problems.append(
                (node.lineno, f"{node.name}: mutable default argument")
            )


def _check_dict_keys(node, problems: list) -> None:
    seen = set()
    for key in node.keys:
        # ast constant keys are always hashable (str/num/bytes/
        # None/bool); tuples parse as ast.Tuple, not Constant
        if isinstance(key, ast.Constant):
            if key.value in seen:
                problems.append(
                    (key.lineno, f"duplicate dict key {key.value!r}")
                )
            seen.add(key.value)


def check_file(path: Path):
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    lines = source.splitlines()
    problems = []

    is_test = "tests" in path.parts
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_function(node, lines, is_test, problems)
        elif isinstance(node, ast.Dict):
            _check_dict_keys(node, problems)

    if path.name != "__init__.py":
        tracker = ImportTracker(lines)
        tracker.visit(tree)
        tracker.finish(tree)
        problems.extend(tracker.problems)
    return problems


def main() -> int:
    root = Path(__file__).resolve().parent.parent
    failures = 0
    for path in iter_files(root):
        for lineno, message in sorted(check_file(path)):
            print(f"{path.relative_to(root)}:{lineno}: {message}")
            failures += 1
    if failures:
        print(f"lint: {failures} problem(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
