# Shared helpers for the kind-backed gates (conformance-kind.sh,
# kind-smoke.sh). Sourced, not executed. Contract both scripts document:
# exit 3 = this environment cannot run the gate (tooling missing),
# exit 1 = the gate ran and failed, exit 0 = passed.

log() { echo "[$(date -u +%Y-%m-%dT%H:%M:%SZ)] $*" | tee -a "$LOG" >&2; }

fail() { log "$*"; exit 1; }

# require_kind_tools <what-for>: logs every missing tool, exits 3 if any
require_kind_tools() {
  local missing=0 tool
  for tool in kind kubectl; do
    if ! command -v "$tool" >/dev/null 2>&1; then
      log "MISSING: $tool not on PATH"
      missing=1
    fi
  done
  if ! { command -v docker || command -v podman; } >/dev/null 2>&1; then
    log "MISSING: no container engine (docker/podman)"
    missing=1
  fi
  if [ "$missing" -ne 0 ]; then
    log "cannot run $1 in this environment; NOT run"
    exit 3
  fi
}

# boot_kind_cluster <name>: create + arm the delete trap + use-context
boot_kind_cluster() {
  local cluster="$1"
  log "creating kind cluster $cluster"
  kind create cluster --name "$cluster" --wait 180s >>"$LOG" 2>&1 \
    || fail "kind create cluster FAILED (see $LOG)"
  # shellcheck disable=SC2064 — expand the name now, not at trap time
  trap "log 'deleting cluster'; kind delete cluster --name '$cluster' >>'$LOG' 2>&1" EXIT
  kubectl config use-context "kind-$cluster" >>"$LOG" 2>&1
}
