#!/usr/bin/env bash
# Deploy smoke: prove the SHIPPED artifacts converge on a real cluster —
# image builds, kind side-load, `make apply` (CRDs + RBAC + two-container
# Deployment), pod Ready, and one HorizontalAutoscaler driven end to end
# through the deployed controller. The role the reference's
# hack/quick-install.sh flow plays for its users (reference:
# hack/quick-install.sh:40-66).
#
# Usage: hack/kind-smoke.sh [log-file]
# Requires: kind, kubectl, docker/podman. CI provides them
# (.github/workflows/presubmit.yaml `smoke` job); elsewhere the script
# exits 3 after logging what was missing — committed evidence of the
# attempt.
set -uo pipefail
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/kind-smoke.log}"
CLUSTER="${CLUSTER:-karpenter-smoke}"
IMAGE_TAG="${IMAGE_TAG:-smoke}"
: > "$LOG"
. hack/lib-kind.sh

require_kind_tools "the deploy smoke"
boot_kind_cluster "$CLUSTER"

log "building + side-loading image (CPU jax: kind nodes have no TPU)"
make kind-load IMAGE_TAG="$IMAGE_TAG" JAX_EXTRAS= >>"$LOG" 2>&1 \
  || fail "make kind-load FAILED"

log "applying CRDs + RBAC + deployment"
make apply IMAGE_TAG="$IMAGE_TAG" JAX_EXTRAS= >>"$LOG" 2>&1 \
  || fail "make apply FAILED"

# the stock manifest targets GKE TPU node pools and expects cert-manager
# for the webhook; a kind smoke drops the node pin, runs the fake
# provider, and skips the webhook listener (admission still runs
# in-store) — everything else (image, RBAC, probes, two containers) is
# exactly what ships
log "patching deployment for the kind environment"
kubectl -n karpenter patch deployment karpenter-tpu --type=json -p '[
  {"op": "remove", "path": "/spec/template/spec/nodeSelector"},
  {"op": "replace", "path": "/spec/replicas", "value": 1},
  {"op": "replace", "path": "/spec/template/spec/containers/0/args", "value": [
    "--apiserver=https://kubernetes.default.svc",
    "--cloud-provider=fake",
    "--solver-uri=127.0.0.1:9090"
  ]}
]' >>"$LOG" 2>&1 || fail "deployment patch FAILED"

log "waiting for the two-container pod to become Ready"
kubectl -n karpenter rollout status deployment/karpenter-tpu \
  --timeout=300s >>"$LOG" 2>&1 || {
  kubectl -n karpenter get pods -o wide >>"$LOG" 2>&1
  kubectl -n karpenter describe pods >>"$LOG" 2>&1
  fail "deployment never became Ready"
}
containers=$(kubectl -n karpenter get pods \
  -l app=karpenter-tpu \
  -o jsonpath='{.items[0].spec.containers[*].name}')
log "pod containers: $containers"
case "$containers" in
  *controller*solver*|*solver*controller*) ;;
  *) fail "expected the two-container pod (controller + solver), got: $containers" ;;
esac

log "driving one HA end to end through the deployed controller"
kubectl apply -f - >>"$LOG" 2>&1 <<'EOF'
apiVersion: autoscaling.karpenter.sh/v1alpha1
kind: MetricsProducer
metadata:
  name: smoke-capacity
  namespace: default
spec:
  reservedCapacity:
    nodeSelector:
      kubernetes.io/os: linux
---
apiVersion: autoscaling.karpenter.sh/v1alpha1
kind: ScalableNodeGroup
metadata:
  name: smoke-group
  namespace: default
spec:
  replicas: 1
  type: FakeNodeGroup
  id: smoke-group
---
apiVersion: autoscaling.karpenter.sh/v1alpha1
kind: HorizontalAutoscaler
metadata:
  name: smoke-group
  namespace: default
spec:
  scaleTargetRef:
    apiVersion: autoscaling.karpenter.sh/v1alpha1
    kind: ScalableNodeGroup
    name: smoke-group
  minReplicas: 1
  maxReplicas: 5
  metrics:
    - prometheus:
        query: karpenter_reserved_capacity_cpu_utilization{name="smoke-capacity"}
        target:
          type: Utilization
          value: 60
EOF

active() {
  kubectl get "$1" "$2" -o \
    jsonpath='{.status.conditions[?(@.type=="Active")].status}' 2>/dev/null
}
deadline=$((SECONDS + 180))
until [ "$(active metricsproducer smoke-capacity)" = "True" ] \
   && [ "$(active horizontalautoscaler smoke-group)" = "True" ] \
   && [ "$(active scalablenodegroup smoke-group)" = "True" ]; do
  if [ "$SECONDS" -ge "$deadline" ]; then
    kubectl get metricsproducer,horizontalautoscaler,scalablenodegroup \
      -o yaml >>"$LOG" 2>&1
    kubectl -n karpenter logs deployment/karpenter-tpu -c controller \
      --tail=100 >>"$LOG" 2>&1
    fail "resources never converged Active=True"
  fi
  sleep 3
done
log "MP + HA + SNG all Active=True through the deployed controller"
log "deploy smoke PASSED"
