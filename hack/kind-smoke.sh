#!/usr/bin/env bash
# Deploy smoke: prove the SHIPPED artifacts converge on a real cluster —
# image build, kind side-load, then `kubectl kustomize config/ |
# hack/smoke-manifest.py | kubectl apply` (the smoke transform strips
# only what a bare kind cluster cannot satisfy: cert-manager certs,
# ServiceMonitor, failurePolicy=Fail webhooks, TPU claims, the GKE node
# pin — do NOT use `make apply` here, it ships those as-is and wedges),
# two-container pod Ready, and one HorizontalAutoscaler driven end to
# end through the deployed controller. The role the reference's
# hack/quick-install.sh flow plays for its users (reference:
# hack/quick-install.sh:40-66).
#
# Usage: hack/kind-smoke.sh [log-file]
# Requires: kind, kubectl, docker/podman. CI provides them
# (.github/workflows/presubmit.yaml `smoke` job); elsewhere the script
# exits 3 after logging what was missing — committed evidence of the
# attempt.
set -uo pipefail
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/kind-smoke.log}"
CLUSTER="${CLUSTER:-karpenter-smoke}"
IMAGE_TAG="${IMAGE_TAG:-smoke}"
: > "$LOG"
. hack/lib-kind.sh

require_kind_tools "the deploy smoke"
boot_kind_cluster "$CLUSTER"

log "building + side-loading image (CPU jax: kind nodes have no TPU)"
make kind-load IMAGE_TAG="$IMAGE_TAG" JAX_EXTRAS= >>"$LOG" 2>&1 \
  || fail "make kind-load FAILED"

# the stock tree targets production GKE (cert-manager certs,
# ServiceMonitor, failurePolicy=Fail webhooks, TPU resource claims, GKE
# node pin) — hack/smoke-manifest.py strips exactly those for a bare
# kind cluster and keeps everything else as shipped (image, RBAC,
# probes, the two-container split)
log "applying CRDs + RBAC + deployment (smoke-transformed manifest)"
kubectl kustomize config/ \
  | python3 hack/smoke-manifest.py "karpenter-tpu:$IMAGE_TAG" \
  | kubectl apply -f - >>"$LOG" 2>&1 || fail "apply FAILED"

log "waiting for the two-container pod to become Ready"
kubectl -n karpenter rollout status deployment/karpenter-tpu \
  --timeout=300s >>"$LOG" 2>&1 || {
  kubectl -n karpenter get pods -o wide >>"$LOG" 2>&1
  kubectl -n karpenter describe pods >>"$LOG" 2>&1
  fail "deployment never became Ready"
}
containers=$(kubectl -n karpenter get pods \
  -l app=karpenter-tpu \
  -o jsonpath='{.items[0].spec.containers[*].name}')
log "pod containers: $containers"
case "$containers" in
  *controller*solver*|*solver*controller*) ;;
  *) fail "expected the two-container pod (controller + solver), got: $containers" ;;
esac

log "driving one HA end to end through the deployed controller"
kubectl apply -f - >>"$LOG" 2>&1 <<'EOF'
apiVersion: autoscaling.karpenter.sh/v1alpha1
kind: MetricsProducer
metadata:
  name: smoke-capacity
  namespace: default
spec:
  reservedCapacity:
    nodeSelector:
      kubernetes.io/os: linux
---
apiVersion: autoscaling.karpenter.sh/v1alpha1
kind: ScalableNodeGroup
metadata:
  name: smoke-group
  namespace: default
spec:
  replicas: 1
  type: FakeNodeGroup
  id: smoke-group
---
apiVersion: autoscaling.karpenter.sh/v1alpha1
kind: HorizontalAutoscaler
metadata:
  name: smoke-group
  namespace: default
spec:
  scaleTargetRef:
    apiVersion: autoscaling.karpenter.sh/v1alpha1
    kind: ScalableNodeGroup
    name: smoke-group
  minReplicas: 1
  maxReplicas: 5
  metrics:
    - prometheus:
        query: karpenter_reserved_capacity_cpu_utilization{name="smoke-capacity"}
        target:
          type: Utilization
          value: 60
EOF

active() {
  kubectl get "$1" "$2" -o \
    jsonpath='{.status.conditions[?(@.type=="Active")].status}' 2>/dev/null
}
deadline=$((SECONDS + 180))
until [ "$(active metricsproducer smoke-capacity)" = "True" ] \
   && [ "$(active horizontalautoscaler smoke-group)" = "True" ] \
   && [ "$(active scalablenodegroup smoke-group)" = "True" ]; do
  if [ "$SECONDS" -ge "$deadline" ]; then
    kubectl get metricsproducer,horizontalautoscaler,scalablenodegroup \
      -o yaml >>"$LOG" 2>&1
    kubectl -n karpenter logs deployment/karpenter-tpu -c controller \
      --tail=100 >>"$LOG" 2>&1
    fail "resources never converged Active=True"
  fi
  sleep 3
done
log "MP + HA + SNG all Active=True through the deployed controller"
log "deploy smoke PASSED"
