# syntax=docker/dockerfile:1
# Container image for the two-container karpenter-tpu pod
# (config/manager/manager.yaml): the same image serves
#   - the controller:  karpenter-tpu  (console script -> karpenter_tpu.__main__)
#   - the solver:      python -m karpenter_tpu.sidecar --port=9090
# The reference publishes with ko (reference Makefile `publish`/`apply`,
# ko resolve over config/); the analog here is `make image` / `make apply`.
#
# Build args:
#   JAX_EXTRAS=tpu   bake the libtpu PJRT plugin for GKE TPU node pools
#                    (default; the same install falls back to CPU off-TPU,
#                    so one image serves both containers)
#   JAX_EXTRAS=      CPU-only image (CI, kind clusters)

FROM python:3.12-slim AS build
ARG JAX_EXTRAS=tpu
# gcc: compiles the native C accelerators (karpenter_tpu/native) at build
# time so the runtime layer needs no toolchain and can run read-only
RUN apt-get update \
    && apt-get install -y --no-install-recommends gcc libc6-dev \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY pyproject.toml README.md ./
COPY karpenter_tpu ./karpenter_tpu
RUN if [ -n "$JAX_EXTRAS" ]; then \
        pip install --no-cache-dir ".[$JAX_EXTRAS]"; \
    else \
        pip install --no-cache-dir .; \
    fi
# Pre-build the C accelerators into the INSTALLED package and prove the
# degraded-mode (no-TPU) solver path imports cleanly. Run from / so the
# /src/karpenter_tpu source tree cannot shadow site-packages (stdin
# scripts put the cwd on sys.path): with the shadow, the kernels built
# into /src — which the runtime layer never copies — and the shipped
# read-only image silently degraded to the pure-numpy solve. The
# __file__ assertion makes that regression loud.
WORKDIR /
RUN python - <<'EOF'
import karpenter_tpu
assert "site-packages" in karpenter_tpu.__file__, (
    f"prebuild imported the wrong tree: {karpenter_tpu.__file__}"
)
from karpenter_tpu.native import load_kbinpack, load_kquantity
assert load_kquantity() is not None, "quantity kernel build failed"
assert load_kbinpack() is not None, "binpack kernel build failed"
# the loader builds next to the imported module, so a successful load
# plus the site-packages __file__ assertion above proves the kernels
# landed in the tree the runtime layer copies
import glob, os
built = glob.glob(os.path.join(
    os.path.dirname(karpenter_tpu.__file__), "native", "_build", "*.so"
))
print("native kernels prebuilt into", built)
EOF

FROM python:3.12-slim
# Runtime layer: python + installed site-packages only (no toolchain).
COPY --from=build /usr/local/lib/python3.12/site-packages /usr/local/lib/python3.12/site-packages
COPY --from=build /usr/local/bin/karpenter-tpu /usr/local/bin/karpenter-tpu
# Non-root, read-only-friendly (webhook certs + JAX caches live in /tmp).
RUN useradd --uid 65532 --no-create-home karpenter
USER 65532
ENV PYTHONUNBUFFERED=1
ENTRYPOINT ["karpenter-tpu"]
